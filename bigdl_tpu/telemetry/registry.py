"""Unified metric registry — counters, gauges, reservoir histograms.

One implementation for every host-side metric in the stack: the training
driver's phase accumulators (``utils/metrics.Metrics`` is now a thin
veneer over this), the serving engine's counters/latency reservoirs
(``serving/metrics.ServingMetrics``), and the runtime watchdogs
(``telemetry/watchdog.py``).  The lineage kept three separate ad-hoc
implementations (reference ``Metrics.scala`` driver accumulators, the
serving latency ring, bench-local medians); BigDL 2.0's cluster pipeline
(arXiv:2204.01715 §4) treats one metrics substrate as the foundation the
optimizer and dashboard both stand on — this is that substrate.

Everything here is host-side bookkeeping: no jax import, no device work,
no syncs.  That property is what makes the telemetry subsystem provably
inert (see ``telemetry/tracer.py``).

Thread safety: metric creation is serialized by the registry lock
(get-or-create is atomic — concurrent threads asking for the same name
get the SAME metric object); each metric serializes its own updates.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class Counter:
    """Monotonic count (requests, recompiles, stall events)."""

    __slots__ = ("name", "_lock", "_v")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._v = 0  # write-guarded-by: _lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    """Last-written value (queue depth, memory watermark, fractions)."""

    __slots__ = ("name", "_v")

    def __init__(self, name: str):
        self.name = name
        self._v: float = 0.0

    def set(self, v: float) -> None:
        self._v = float(v)

    @property
    def value(self) -> float:
        return self._v


class Reservoir:
    """Fixed-size ring of recent values — the sliding-window percentile
    estimator (p50/p95/p99 over the most recent ``capacity`` samples).

    A bounded ring instead of an unbounded list: an always-on endpoint
    must not grow memory with request count.  This is the one reservoir
    implementation in the tree; ``serving.metrics.LatencyReservoir`` is
    an alias of it.
    """

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._buf = [0.0] * capacity  # guarded-by: _lock
        # total ever recorded; write-guarded-by: _lock
        self._n = 0

    def record(self, value: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = value
            self._n += 1

    @property
    def count(self) -> int:
        """Total values ever recorded (not just the retained window)."""
        return self._n

    def window(self) -> List[float]:
        """Copy of the retained sample window (unordered) — what the
        set-level aggregation concatenates to compute cross-replica
        percentiles (``ServingMetrics.aggregate``)."""
        with self._lock:
            n = min(self._n, len(self._buf))
            return list(self._buf[:n])

    def percentiles(self, qs=(50, 95, 99)) -> Optional[Dict[str, float]]:
        with self._lock:
            n = min(self._n, len(self._buf))
            if n == 0:
                return None
            window = sorted(self._buf[:n])
        out = {}
        for q in qs:
            # nearest-rank percentile over the window
            idx = min(n - 1, max(0, int(round(q / 100.0 * n)) - 1))
            out[f"p{q}"] = window[idx]
        out["mean"] = sum(window) / n
        out["max"] = window[-1]
        return out


class Histogram:
    """Exact sum/count/min/max plus a bounded reservoir for percentiles.

    The exact accumulators are what ``Metrics.summary()`` (driver phase
    accumulators) reads; the reservoir serves the p50/p95/p99 SLO view.
    """

    __slots__ = ("name", "_lock", "_res", "_sum", "_count", "_min", "_max")

    def __init__(self, name: str, capacity: int = 4096):
        self.name = name
        self._lock = threading.Lock()
        self._res = Reservoir(capacity)
        # exact accumulators: one locked writer (observe); the scalar
        # properties read lock-free (stale-but-consistent floats)
        self._sum = 0.0                       # write-guarded-by: _lock
        self._count = 0                       # write-guarded-by: _lock
        self._min: Optional[float] = None     # write-guarded-by: _lock
        self._max: Optional[float] = None     # write-guarded-by: _lock

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
        self._res.record(v)

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def percentiles(self, qs=(50, 95, 99)) -> Optional[Dict[str, float]]:
        return self._res.percentiles(qs)

    @property
    def reservoir(self) -> Reservoir:
        """The backing percentile window (``ServingMetrics`` exposes it
        as the historical ``latency`` attribute; aggregation reads
        ``.window()``)."""
        return self._res

    def snapshot(self) -> dict:
        with self._lock:
            snap = {"count": self._count, "sum": self._sum,
                    "mean": self._sum / self._count if self._count else 0.0,
                    "min": self._min, "max": self._max}
        pct = self._res.percentiles()
        if pct is not None:
            snap.update({k: pct[k] for k in ("p50", "p95", "p99")})
        return snap


class MetricRegistry:
    """Get-or-create registry of named metrics, snapshot-exportable.

    Names are flat strings; the convention is ``scope/name``
    (``driver/device_wait_fraction``, ``telemetry/recompiles``,
    ``serving/rows_dispatched``).  Asking for an existing name with a
    different metric type is a bug and raises.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}  # guarded-by: _lock

    def _get_or_create(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str, capacity: int = 4096) -> Histogram:
        return self._get_or_create(name, Histogram, capacity)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> dict:
        """JSON-able snapshot: ``{"counters": {name: int}, "gauges":
        {name: float}, "histograms": {name: {count, sum, mean, min,
        max, p50, p95, p99}}}``."""
        with self._lock:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][name] = m.snapshot()
        return out

    def gauges(self) -> Dict[str, float]:
        """Flat name → value of gauges only — cheap enough for a
        per-block poll (no histogram-reservoir sorting)."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.value for name, m in items
                if isinstance(m, Gauge)}

    def scalars(self) -> Dict[str, float]:
        """Flat name → scalar view (counters/gauges as-is, histograms as
        their mean) — what the driver mirrors into ``TrainSummary``."""
        with self._lock:
            items = list(self._metrics.items())
        out = {}
        for name, m in items:
            out[name] = m.mean if isinstance(m, Histogram) else m.value
        return out

    def discard(self, name: str) -> None:
        """Remove one metric if present (``Metrics.reset`` uses this to
        clear only the accumulators it owns on a SHARED registry)."""
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Drop every metric.  NOTE: holders of direct metric-object
        references (watchdog counters) keep updating orphaned objects
        after this — on a shared registry prefer :meth:`discard` of the
        names you own."""
        with self._lock:
            self._metrics.clear()
