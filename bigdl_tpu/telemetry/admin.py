"""Admin plane — /metrics, /healthz, /trace, /flight, /profile over HTTP.

BigDL 2.0 Cluster Serving treats external monitoring of the serving
pipeline as a product surface (arXiv:2204.01715 §4 — the dashboard);
here that surface is a lightweight stdlib ``http.server`` thread and
the first HTTP beachhead for ROADMAP item 1's RPC front end:

- ``GET /metrics`` — Prometheus text exposition (v0.0.4) rendered from
  the registered :class:`~bigdl_tpu.telemetry.registry.MetricRegistry`
  snapshots: counters, gauges, and histograms as summaries with
  p50/p95/p99 quantiles — which includes the per-row-bucket serving
  latency reservoirs (``serving/latency_s_bucket{N}``).  Sources are
  distinguished by a ``source`` label, so a ReplicaSet's per-replica
  registries and its set-level resilience counters scrape as one page.
- ``GET /healthz`` — JSON health: every registered provider's verdict
  (ReplicaSet health states, registry breaker states, driver watchdog
  verdicts); HTTP 200 when every source reports ``ok``, 503 otherwise.
- ``GET /trace`` — the bounded telemetry tracer(s), dumped on demand
  as Chrome-trace JSON (one pid per source, mergeable in Perfetto).
- ``GET /flight`` — the flight-recorder ring as JSON.
- ``GET /profile?seconds=N`` — on-demand ``jax.profiler`` capture via
  the ``utils/profiling`` bridge; returns the xplane log dir.  The one
  endpoint that may sync the device — it exists to be the opt-in deep
  dive, never scraped.

Security posture (documented in the README): binds ``127.0.0.1`` ONLY
by default and is OFF by default (``Config.admin_port = 0``); there is
no auth — anything that can reach the port can read metrics and
trigger a profile, so a non-loopback bind is an explicit, logged
choice.

Inertness contract: with ``admin_port == 0`` nothing here is ever
constructed — no socket, no thread (the zero-extra-threads gate in
``tests/test_obs_plane.py``).  The serving/driver hot paths never call
into this module; the scrape path only READS registry snapshots (each
under its own lock) — rendering cost is paid by the scraper's thread,
measured by ``bench.py --serving``'s ``admin_scrape_overhead`` point.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger("bigdl_tpu.telemetry")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "bigdl_tpu_"
_MAX_PROFILE_S = 60.0


def _prom_name(name: str) -> str:
    """``serving/latency_s`` → ``bigdl_tpu_serving_latency_s``."""
    return _PREFIX + _NAME_RE.sub("_", name)


def _prom_escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def render_prometheus(snapshots: Dict[str, dict]) -> str:
    """Prometheus text exposition from ``{source: registry.snapshot()}``.

    Families are merged across sources (one ``# TYPE`` header per
    metric name); every sample carries a ``source`` label.  Histograms
    render as summaries: ``quantile``-labelled samples from the
    reservoir percentiles plus ``_sum``/``_count`` from the exact
    accumulators.
    """
    counters: Dict[str, list] = {}
    gauges: Dict[str, list] = {}
    summaries: Dict[str, list] = {}
    for source, snap in sorted(snapshots.items()):
        lbl = f'{{source="{_prom_escape(source)}"}}'
        for name, v in sorted((snap.get("counters") or {}).items()):
            counters.setdefault(_prom_name(name), []).append(
                f"{_prom_name(name)}{lbl} {v}")
        for name, v in sorted((snap.get("gauges") or {}).items()):
            gauges.setdefault(_prom_name(name), []).append(
                f"{_prom_name(name)}{lbl} {v}")
        for name, h in sorted((snap.get("histograms") or {}).items()):
            pn = _prom_name(name)
            rows = summaries.setdefault(pn, [])
            src = _prom_escape(source)
            for q in ("p50", "p95", "p99"):
                if h.get(q) is not None:
                    rows.append(
                        f'{pn}{{source="{src}",quantile="0.{q[1:]}"}} '
                        f"{h[q]}")
            rows.append(f'{pn}_sum{{source="{src}"}} {h.get("sum", 0.0)}')
            rows.append(f'{pn}_count{{source="{src}"}} {h.get("count", 0)}')
    lines = []
    for fam, kind in ((counters, "counter"), (gauges, "gauge"),
                      (summaries, "summary")):
        for name in sorted(fam):
            lines.append(f"# TYPE {name} {kind}")
            lines.extend(fam[name])
    return "\n".join(lines) + ("\n" if lines else "")


class AdminServer:
    """One process-local admin HTTP endpoint (see module docstring).

    Sources register by name; registration replaces (idempotent — a
    redeployed service under the same name just swaps its registry in).
    ``port=0`` binds an ephemeral port (tests); ``.port`` reports the
    bound one.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 profile_dir: Optional[str] = None):
        self.host = host
        self.requested_port = int(port)
        self.profile_dir = profile_dir
        self.port: Optional[int] = None
        self._lock = threading.Lock()
        self._registries: Dict[str, object] = {}  # guarded-by: _lock
        self._tracers: Dict[str, object] = {}     # guarded-by: _lock
        # guarded-by: _lock
        self._health: Dict[str, Callable[[], dict]] = {}
        # names handed out, not yet bound; guarded-by: _lock
        self._reserved: set = set()
        self._flight = None  # write-guarded-by: _lock
        self._profile_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        if host not in ("127.0.0.1", "localhost", "::1"):
            logger.warning(
                "admin plane binding non-loopback host %r — there is no "
                "auth on this surface; make sure the network trusts it",
                host)

    # ------------------------------------------------------ registration
    def add_registry(self, name: str, registry) -> "AdminServer":
        with self._lock:
            self._registries[name] = registry
        return self

    def add_tracer(self, name: str, tracer) -> "AdminServer":
        with self._lock:
            self._tracers[name] = tracer
        return self

    def add_health(self, name: str,
                   provider: Callable[[], dict]) -> "AdminServer":
        """``provider()`` returns a JSON-able dict; an ``"ok"`` key
        (when present) feeds the top-level verdict/status code."""
        with self._lock:
            self._health[name] = provider
        return self

    def set_flight(self, recorder) -> "AdminServer":
        with self._lock:
            self._flight = recorder
        return self

    def drop_tracer(self, name: str) -> None:
        """Unregister just the tracer under ``name`` (a driver rerun
        with telemetry off must not keep serving the previous run's
        trace as current)."""
        with self._lock:
            self._tracers.pop(name, None)

    def drop_health(self, name: str) -> None:
        """Unregister just the health provider under ``name``."""
        with self._lock:
            self._health.pop(name, None)

    def remove_source(self, name: str) -> None:
        """Drop every registration under ``name`` (registry, tracer,
        health) and release its reservation.  Stopped services MUST
        call this (their ``stop()`` does): a retired ReplicaSet left
        registered would hold its metrics alive forever and report its
        parked replicas as a permanent ``/healthz`` 503."""
        with self._lock:
            self._registries.pop(name, None)
            self._tracers.pop(name, None)
            self._health.pop(name, None)
            self._reserved.discard(name)

    def unique_source_name(self, base: str) -> str:
        """``base`` if unused, else ``base-2``, ``base-3``, ... —
        for sources with no natural unique name (two concurrent
        training drivers must not silently overwrite each other's
        ``driver`` registration).  The returned name is RESERVED
        atomically (two racing callers cannot both get ``base``);
        ``remove_source`` releases it."""
        with self._lock:
            taken = (self._registries.keys() | self._tracers.keys()
                     | self._health.keys() | self._reserved)
            name = base
            if name in taken:
                k = 2
                while f"{base}-{k}" in taken:
                    k += 1
                name = f"{base}-{k}"
            self._reserved.add(name)
            return name

    # -------------------------------------------------------- rendering
    def metrics_text(self) -> str:
        with self._lock:
            regs = dict(self._registries)
        return render_prometheus(
            {name: reg.snapshot() for name, reg in regs.items()})

    def health_json(self) -> dict:
        with self._lock:
            providers = dict(self._health)
        sources, ok = {}, True
        for name, fn in sorted(providers.items()):
            try:
                verdict = fn()
            except Exception as e:  # a broken probe IS a health signal
                verdict = {"ok": False,
                           "error": f"{type(e).__name__}: {e}"}
            sources[name] = verdict
            if isinstance(verdict, dict) and verdict.get("ok") is False:
                ok = False
        return {"ok": ok, "sources": sources}

    def trace_json(self) -> dict:
        """All registered tracers merged into one Chrome trace — one
        pid per source so Perfetto shows them as separate processes.
        Deduplicated by tracer IDENTITY: a ReplicaSet and its replicas
        legitimately register the same shared Tracer under N+1 names,
        which must export once, not N+1 times."""
        with self._lock:
            tracers = dict(self._tracers)
        events = []
        seen: Dict[int, str] = {}
        pid = 0
        for name, tr in sorted(tracers.items()):
            if id(tr) in seen:
                continue
            seen[id(tr)] = name
            sub = tr.to_chrome_trace(process_name=name)
            for ev in sub["traceEvents"]:
                ev = dict(ev)
                ev["pid"] = pid
                events.append(ev)
            pid += 1
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"sources": sorted(seen.values())}}

    def flight_json(self) -> dict:
        with self._lock:
            fl = self._flight
        if fl is None:
            return {"meta": None, "events": []}
        return {"meta": fl.meta, "events": fl.events()}

    def profile(self, seconds: float) -> dict:
        """On-demand jax profiler capture (the ``utils/profiling``
        bridge) — serialized: one capture at a time."""
        seconds = max(0.1, min(float(seconds), _MAX_PROFILE_S))
        if not self._profile_lock.acquire(blocking=False):
            raise RuntimeError("a profile capture is already running")
        try:
            from bigdl_tpu.utils.profiling import profile_window
            with self._lock:
                tracer = next(iter(self._tracers.values()), None)
            log_dir = profile_window(seconds, log_dir=self.profile_dir,
                                     tracer=tracer)
            return {"log_dir": log_dir, "seconds": seconds}
        finally:
            self._profile_lock.release()

    # -------------------------------------------------------- lifecycle
    def start(self) -> int:
        """Bind + serve on a daemon thread; idempotent.  Returns the
        bound port."""
        if self._httpd is not None:
            return self.port
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # stdlib default spams
                logger.debug("admin: " + fmt, *args)

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib API
                url = urlparse(self.path)
                try:
                    if url.path == "/metrics":
                        self._send(200, server.metrics_text().encode(),
                                   "text/plain; version=0.0.4")
                    elif url.path == "/healthz":
                        h = server.health_json()
                        self._send(200 if h["ok"] else 503,
                                   json.dumps(h).encode(),
                                   "application/json")
                    elif url.path == "/trace":
                        self._send(200,
                                   json.dumps(server.trace_json()).encode(),
                                   "application/json")
                    elif url.path == "/flight":
                        self._send(
                            200, json.dumps(server.flight_json(),
                                            default=str).encode(),
                            "application/json")
                    elif url.path == "/profile":
                        q = parse_qs(url.query)
                        secs = float(q.get("seconds", ["3"])[0])
                        self._send(200,
                                   json.dumps(server.profile(secs)).encode(),
                                   "application/json")
                    else:
                        self._send(404, json.dumps(
                            {"error": f"no route {url.path}",
                             "routes": ["/metrics", "/healthz", "/trace",
                                        "/flight", "/profile"]}).encode(),
                            "application/json")
                except Exception as e:
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")

        self._httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="bigdl-tpu-admin",
            daemon=True)
        self._thread.start()
        logger.info("admin plane listening on http://%s:%d "
                    "(/metrics /healthz /trace /flight /profile)",
                    self.host, self.port)
        return self.port

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def url(self, path: str = "/") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "AdminServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ------------------------------------------------- process-wide singleton
_install_lock = threading.Lock()
# write-guarded-by: _install_lock
_installed: Optional[AdminServer] = None


def install(server: Optional[AdminServer]) -> None:
    """Install (or clear) the process-wide admin server that serving /
    driver constructors register their sources with."""
    global _installed
    with _install_lock:
        _installed = server


def current() -> Optional[AdminServer]:
    return _installed


_start_failed = False  # write-guarded-by: _install_lock


def maybe_start() -> Optional[AdminServer]:
    """Start-and-install the admin plane per ``Config.admin_port`` /
    ``BIGDL_TPU_ADMIN_PORT`` (0 = off → None, the zero-thread inert
    state).  Idempotent; an explicitly installed server wins.

    A bind failure (port already taken) DEGRADES monitoring, never the
    product: it is logged once and remembered — serving/training
    constructors keep working without an admin plane instead of
    crashing on an observability knob."""
    global _installed, _start_failed
    if _installed is not None:
        return _installed
    if _start_failed:
        return None
    from bigdl_tpu.utils.config import get_config
    port = int(getattr(get_config(), "admin_port", 0) or 0)
    if port <= 0:
        return None
    with _install_lock:
        if _installed is None and not _start_failed:
            srv = AdminServer(port=port)
            try:
                srv.start()
            except OSError as e:
                _start_failed = True
                logger.warning(
                    "admin plane could not bind 127.0.0.1:%d (%s) — "
                    "monitoring disabled for this process, serving/"
                    "training unaffected", port, e)
                return None
            _installed = srv
    return _installed


def reset() -> None:
    """Stop + drop the singleton (tests)."""
    global _installed, _start_failed
    with _install_lock:
        if _installed is not None:
            _installed.stop()
        _installed = None
        _start_failed = False
