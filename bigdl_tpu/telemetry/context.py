"""Request-scoped trace context — the wire-level observability unit.

PR 6 made the *process* observable (step timeline, one metric
registry); nothing was *request*-scoped: when the ReplicaSet fails a
request over or a breaker reroutes a deploy, there is no way to answer
"what happened to request X".  A :class:`RequestContext` is minted at
``submit()`` (or supplied by the caller — the future RPC front end of
ROADMAP item 1 will mint it from wire headers) and travels WITH the
request through the batcher queue, the coalesced dispatch, and every
ReplicaSet failover hop:

- ``trace_id`` correlates the request across the tracer (span args +
  Chrome flow events fanning N coalesced request spans into their one
  dispatch span), the flight recorder (failover/quarantine events carry
  it), and whatever the caller logs;
- ``hops`` is the request's routing history — one entry per replica
  attempt, outcome stamped at completion — so a failed-over request
  carries its full story ("r0: ReplicaDeadError → r2: ok");
- ``tenant`` tags the submitting principal (admission control / QoS
  classes build on this — ROADMAP item 1c);
- ``deadline`` mirrors the request deadline already propagated by the
  serving queue (monotonic seconds; the context never *enforces* it —
  the batcher does — it only records it for the post-mortem).

Inertness contract (house discipline): with ``Config.request_tracing``
off and no explicit context passed, NO context object is ever
allocated — every call site guards on ``ctx is not None``, so the off
path is byte-identical to the pre-context engine (gated in
``tests/test_obs_plane.py``).  Everything here is host-side
bookkeeping: no jax import, no device work, no syncs.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional

# process-unique trace-id prefix + a monotone counter: unique across
# processes (pid + start-time entropy from the clock) without touching
# any RNG — ids must be mintable from any thread at request rate.  The
# counter keeps 32 bits (4.3e9 mints per process before wrapping — far
# beyond any process lifetime at request rate; a 16-bit counter would
# recycle ids within minutes under bench-level load and silently merge
# two requests' stories in obs_report)
_PREFIX = f"{os.getpid() & 0xffff:04x}{(time.time_ns() >> 10) & 0xffff:04x}"
_LOCK = threading.Lock()
_SEQ = itertools.count(1)  # guarded-by: _LOCK


def new_trace_id() -> str:
    """16-hex-char id — pid(4) + start-time(4) + counter(8) hex —
    unique within a process for 2**32 mints and (practically) across
    processes; cheap enough to mint per request."""
    with _LOCK:
        n = next(_SEQ)
    return f"{_PREFIX}{n & 0xffffffff:08x}"


def flow_id(trace_id: str) -> int:
    """Chrome-trace flow-event id for a trace id (positive int63 —
    Perfetto binds ``s``/``f`` events sharing this id into one arrow)."""
    return int(trace_id, 16) & 0x7FFFFFFFFFFFFFFF


class RequestContext:
    """Per-request trace context (see module docstring).

    Mutable by design: the router appends ``hops`` as it retries, and
    the dispatch path stamps the coalesced bucket — the caller that
    kept a reference reads the full story after the future resolves.
    """

    __slots__ = ("trace_id", "tenant", "deadline", "parent", "hops",
                 "t_minted")

    def __init__(self, trace_id: Optional[str] = None,
                 tenant: Optional[str] = None,
                 deadline: Optional[float] = None,
                 parent: Optional[str] = None):
        self.trace_id = trace_id or new_trace_id()
        self.tenant = tenant
        self.deadline = deadline
        self.parent = parent  # parent span/trace id (RPC propagation)
        self.hops: List[Dict] = []
        self.t_minted = time.monotonic()

    @property
    def flow_id(self) -> int:
        return flow_id(self.trace_id)

    def add_hop(self, replica: int, probe: bool = False) -> Dict:
        """Record one routing attempt; the returned dict is stamped
        with ``outcome`` at completion ("ok" / exception name)."""
        hop = {"replica": int(replica), "probe": bool(probe),
               "outcome": None}
        self.hops.append(hop)
        return hop

    def snapshot(self) -> dict:
        """JSON-able view (what the flight recorder / obs_report see)."""
        return {"trace_id": self.trace_id, "tenant": self.tenant,
                "parent": self.parent, "hops": [dict(h) for h in self.hops]}

    def __repr__(self) -> str:
        hops = ",".join(
            f"r{h['replica']}:{h['outcome'] or '?'}" for h in self.hops)
        return (f"RequestContext({self.trace_id}"
                + (f", tenant={self.tenant!r}" if self.tenant else "")
                + (f", hops=[{hops}]" if hops else "") + ")")
