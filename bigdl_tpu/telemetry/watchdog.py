"""Runtime watchdogs — recompiles, pipeline stalls, memory watermarks.

graftlint catches recompile/host-sync hazards statically (GL106/GL107);
these watchdogs enforce the same discipline AT RUNTIME, where dynamic
shapes and data-dependent paths live.  All of them are observers: they
read cheap host-side state (jit cache sizes, span durations, allocator
stats), record findings into the :class:`~bigdl_tpu.telemetry.registry.
MetricRegistry` and the tracer, and log warnings — they never touch the
computation.

- :class:`RecompileWatchdog` — jit cache-size delta per dispatched
  block.  The first compile of a key (a new K-block length, a deploy's
  AOT warmup) is expected and free; any growth AFTER that is a
  steady-state retrace — the throughput cliff GL106 exists to prevent.
- :class:`StallDetector` — per-block host-phase accounting.  The driver
  reports how long each block spent in staging (host-stack + H2D),
  dispatch enqueue, the one-block-behind device wait, and trigger
  replay.  Stager starvation = staging dominates while the device wait
  is ~zero (the device is idle waiting for input).  Host-sync stall =
  a dispatch enqueue that took milliseconds (issuing an async jit call
  is microseconds; a blocking enqueue means a hidden host sync or a
  full device queue).
- :class:`MemoryWatermark` — ``device.memory_stats()`` gauges where the
  backend exposes them (TPU does; CPU returns nothing — the gauges just
  stay absent).  Reading allocator stats is a host call, not a sync.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.telemetry.registry import MetricRegistry
from bigdl_tpu.telemetry.tracer import Tracer

logger = logging.getLogger("bigdl_tpu.telemetry")


def jit_cache_size(fn) -> Optional[int]:
    """Compiled-signature count of a ``jax.jit`` wrapper (None when the
    object isn't a jit wrapper or the internal moved)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


class RecompileWatchdog:
    """Flags jit cache growth after a key's first observation.

    ``observe(key, cache_size)`` per dispatched block (or per serving
    traffic window): the first observation of a key records its
    baseline (the planned compile); any later growth is a steady-state
    recompile — counted, traced as an instant event, and warned once
    per occurrence.  ``observe`` with ``cache_size=None`` is a no-op,
    so call sites never need to branch on backend capabilities.
    """

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 tracer: Optional[Tracer] = None, flight=None,
                 trace_id: Optional[str] = None):
        self._seen: Dict[object, int] = {}
        self.events: List[Tuple[object, int, int]] = []  # (key, old, new)
        self._counter = (registry.counter("telemetry/recompiles")
                         if registry is not None else None)
        self._tracer = tracer
        # optional flight recorder (+ the run's trace context): a
        # steady-state recompile is exactly the kind of rare
        # state-change the black box exists to keep
        self._flight = flight
        self._trace_id = trace_id

    def observe(self, key, cache_size: Optional[int]) -> bool:
        """Returns True when this observation flagged a recompile."""
        if cache_size is None:
            return False
        prev = self._seen.get(key)
        self._seen[key] = cache_size
        if prev is None or cache_size <= prev:
            return False
        self.events.append((key, prev, cache_size))
        if self._counter is not None:
            self._counter.inc()
        if self._tracer is not None:
            self._tracer.instant("recompile", key=str(key),
                                 cache_size=cache_size)
        if self._flight is not None:
            self._flight.record("recompile", cat="driver",
                                trace_id=self._trace_id, key=str(key),
                                cache_size=cache_size)
        logger.warning(
            "recompile watchdog: jit cache for %r grew %d -> %d after "
            "warmup — a steady-state retrace (GL106 discipline; check "
            "for shape churn / per-call scalar args)", key, prev,
            cache_size)
        return True

    @property
    def recompile_count(self) -> int:
        return len(self.events)

    @property
    def silent(self) -> bool:
        """No steady-state recompile observed."""
        return not self.events


class StallDetector:
    """Per-block pipeline-phase accounting + stall/starvation flags.

    ``record_block`` takes the four host-accounted phase durations of
    one dispatched block.  Fractions are of the host-accounted total
    (stage + dispatch + wait + replay) — device compute hidden behind
    the pipeline is deliberately not in the denominator; a healthy
    pipelined run shows ``device_wait`` absorbing nearly everything.
    """

    def __init__(self, registry: MetricRegistry,
                 tracer: Optional[Tracer] = None,
                 starvation_threshold: float = 0.5,
                 wait_floor: float = 0.1,
                 dispatch_stall_ms: float = 50.0,
                 warm_blocks: int = 1):
        self._registry = registry
        self._tracer = tracer
        self.starvation_threshold = starvation_threshold
        self.wait_floor = wait_floor
        self.dispatch_stall_ms = dispatch_stall_ms
        self.warm_blocks = warm_blocks
        self._totals = {"stage": 0.0, "dispatch": 0.0,
                        "device_wait": 0.0, "replay": 0.0}
        self._blocks = 0
        self._starvations = registry.counter(
            "telemetry/stager_starvation_events")
        self._sync_stalls = registry.counter(
            "telemetry/host_sync_stall_events")

    def record_block(self, stage_s: float, dispatch_s: float,
                     wait_s: float, replay_s: float,
                     first_compile: bool = False) -> None:
        """``first_compile``: this block's dispatch traced+compiled a
        fresh jit signature — a planned one-off cost, charged to the
        fractions but exempt from the stall flags (compile time is not
        a steady-state host sync)."""
        self._blocks += 1
        t = self._totals
        t["stage"] += stage_s
        t["dispatch"] += dispatch_s
        t["device_wait"] += wait_s
        t["replay"] += replay_s
        fr = self.fractions()
        reg = self._registry
        reg.gauge("driver/host_stage_fraction").set(fr["stage"])
        reg.gauge("driver/dispatch_fraction").set(fr["dispatch"])
        reg.gauge("driver/device_wait_fraction").set(fr["device_wait"])
        reg.gauge("driver/replay_fraction").set(fr["replay"])
        if first_compile or self._blocks <= self.warm_blocks:
            # warmup blocks carry compile/allocator noise — fractions
            # recorded, verdicts withheld (the bench warmup discipline)
            return
        block_total = stage_s + dispatch_s + wait_s + replay_s
        if block_total > 0:
            if (stage_s / block_total > self.starvation_threshold
                    and wait_s / block_total < self.wait_floor):
                self._starvations.inc()
                if self._tracer is not None:
                    self._tracer.instant(
                        "stager_starvation",
                        stage_ms=round(stage_s * 1e3, 3),
                        wait_ms=round(wait_s * 1e3, 3))
        if dispatch_s * 1e3 > self.dispatch_stall_ms:
            self._sync_stalls.inc()
            if self._tracer is not None:
                self._tracer.instant(
                    "host_sync_stall",
                    dispatch_ms=round(dispatch_s * 1e3, 3))
            logger.warning(
                "stall detector: block dispatch enqueue took %.1f ms "
                "(budget %.1f ms) — a hidden host sync or a saturated "
                "device queue is blocking the driver loop",
                dispatch_s * 1e3, self.dispatch_stall_ms)

    def fractions(self) -> Dict[str, float]:
        total = sum(self._totals.values())
        if total <= 0:
            return {k: 0.0 for k in self._totals}
        return {k: v / total for k, v in self._totals.items()}

    @property
    def blocks_observed(self) -> int:
        return self._blocks

    @property
    def starvation_count(self) -> int:
        return self._starvations.value

    @property
    def sync_stall_count(self) -> int:
        return self._sync_stalls.value


class MemoryWatermark:
    """Device-memory gauges from ``device.memory_stats()``.

    TPU runtimes expose ``bytes_in_use`` / ``peak_bytes_in_use``; the
    CPU backend exposes nothing — ``observe`` then returns None and no
    gauges appear.  Reading allocator counters never syncs the device.
    """

    _KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

    def __init__(self, registry: MetricRegistry):
        self._registry = registry
        self.available: Optional[bool] = None  # unknown until first observe

    def observe(self, device=None) -> Optional[dict]:
        if device is None:
            import jax
            device = jax.local_devices()[0]
        try:
            stats = device.memory_stats()
        except Exception:
            stats = None
        if not stats:
            self.available = False
            return None
        self.available = True
        for k in self._KEYS:
            if k in stats:
                self._registry.gauge(f"device/{k}").set(stats[k])
        return stats
