"""Step-timeline tracer — nested host-side spans, Chrome-trace export.

The reference lineage self-times every layer (``AbstractModule.getTimes``)
and prints driver-phase accumulators (``Metrics.summary``).  Under XLA
those observables fused away; what remains measurable is the *pipeline*:
host batch stacking, H2D staging, jit dispatch, device wait, the
one-block-behind loss fetch, trigger/validation/checkpoint work.  This
tracer records exactly those phases as spans and exports them as
Chrome-trace JSON (open in Perfetto / ``chrome://tracing``, summarize
with ``tools/trace_report.py``).

The hard contract — telemetry is PROVABLY INERT:

- a span is two ``time.perf_counter_ns()`` reads and one list append —
  no jax import, no device work, no host↔device sync, ever;
- spans around device fetches wrap fetches the driver already performs
  (the one-block-behind loss fetch — the GL107-safe pattern), never
  introduce one;
- disabled (``enabled=False``), ``span()`` returns one shared no-op
  context manager: zero allocation, zero branching beyond the flag —
  the loss sequence and dispatch count are bitwise identical either way
  (gated in ``tests/test_telemetry.py``).

Event volume is bounded: past ``capacity`` events the tracer drops and
counts (``dropped_events`` rides in the export) — an always-on run may
not grow memory with step count.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple


class _NullSpan:
    """Shared no-op context manager returned by a disabled tracer."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()

# phase categories the driver emits; trace_report computes time shares
# over these (plus "other" for unaccounted wall time)
PHASE_CATS = ("stage", "dispatch", "device_wait", "replay", "trigger")


class _Span:
    __slots__ = ("_tr", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: Optional[str],
                 args: Optional[dict]):
        self._tr = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        self._tr._record("X", self.name, self.cat, self._t0,
                         t1 - self._t0, self.args)
        return False


class Tracer:
    """Thread-safe span recorder with Chrome-trace JSON export.

    Events are stored as tuples ``(ph, name, cat, t0_ns, dur_ns, tid,
    args, flow)`` where ``ph`` is the Chrome phase ("X" complete span,
    "i" instant, "s"/"f" flow start/finish) and ``tid`` is either a
    host thread id or a virtual track name (the driver puts in-flight
    device blocks on a ``"device"`` track so they can overlap host
    spans without breaking nesting).  ``flow`` is the flow-arrow id for
    "s"/"f" events (None otherwise) — the serving engine uses flows to
    fan N coalesced request spans into their one dispatch span.
    """

    def __init__(self, enabled: bool = True, capacity: int = 200_000):
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._events: List[Tuple] = []  # guarded-by: _lock
        self._dropped = 0               # write-guarded-by: _lock

    # -- recording ---------------------------------------------------------
    def span(self, name: str, cat: Optional[str] = None, **args):
        """Context manager timing one host-side phase.  ``cat`` groups
        spans into pipeline phases (see ``PHASE_CATS``); ``args`` ride
        into the Chrome-trace ``args`` field (keep them cheap scalars)."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "watchdog", **args) -> None:
        """Point-in-time marker (watchdog events: recompile, stall)."""
        if not self.enabled:
            return
        self._record("i", name, cat, time.perf_counter_ns(), 0,
                     args or None)

    def flow_start(self, name: str, fid: int, cat: Optional[str] = None,
                   **args) -> None:
        """Open one side of a Chrome flow arrow (``ph:"s"``).  Emit it
        INSIDE an open span on the emitting thread — flow events bind to
        the enclosing slice whose time range contains them.  ``fid``
        pairs starts with finishes (``telemetry.context.flow_id``); the
        request-fan-in edges in the serving trace are N ``flow_start``s
        (one per coalesced request's submit span) finishing in the one
        dispatch span."""
        if not self.enabled:
            return
        self._record("s", name, cat, time.perf_counter_ns(), 0,
                     args or None, flow=fid)

    def flow_end(self, name: str, fid: int, cat: Optional[str] = None,
                 **args) -> None:
        """Close a flow arrow (``ph:"f"``, binding to the ENCLOSING
        slice — ``bp:"e"``); emit inside the consuming span."""
        if not self.enabled:
            return
        self._record("f", name, cat, time.perf_counter_ns(), 0,
                     args or None, flow=fid)

    def record(self, name: str, t0_ns: int, t1_ns: int,
               cat: Optional[str] = None, track: Optional[str] = None,
               **args) -> None:
        """Record a span with explicit endpoints — for durations whose
        start predates the call site (e.g. a dispatched block's
        in-flight window, closed by the one-block-behind fetch).
        ``track`` places it on a named virtual track instead of the
        calling thread."""
        if not self.enabled:
            return
        self._record("X", name, cat, t0_ns, max(0, t1_ns - t0_ns),
                     args or None, tid=track)

    def _record(self, ph, name, cat, t0_ns, dur_ns, args, tid=None,
                flow=None):
        if tid is None:
            tid = threading.get_ident()
        with self._lock:
            if len(self._events) >= self.capacity:
                self._dropped += 1
                return
            self._events.append((ph, name, cat, t0_ns, dur_ns, tid, args,
                                 flow))

    # -- reading -----------------------------------------------------------
    def events(self) -> List[Tuple]:
        with self._lock:
            return list(self._events)

    @property
    def dropped_events(self) -> int:
        return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def phase_totals(self) -> Dict[str, float]:
        """Seconds per span category (instants excluded) — the cheap
        aggregate ``bench._measure`` consumes; the full self-time
        attribution lives in ``tools/trace_report.py``."""
        totals: Dict[str, float] = {}
        for ph, _name, cat, _t0, dur_ns, _tid, _args, _flow in self.events():
            if ph != "X":
                continue
            key = cat or "uncategorized"
            totals[key] = totals.get(key, 0.0) + dur_ns / 1e9
        return totals

    # -- export ------------------------------------------------------------
    def to_chrome_trace(self, process_name: str = "bigdl_tpu") -> dict:
        """Chrome-trace JSON object (``ts``/``dur`` in microseconds,
        which is what Perfetto and ``chrome://tracing`` expect)."""
        events = self.events()
        tid_map: Dict[object, int] = {}

        def tid_of(tid) -> int:
            if tid not in tid_map:
                # virtual tracks get small ids after the host threads
                tid_map[tid] = len(tid_map) + 1
            return tid_map[tid]

        out = []
        for ph, name, cat, t0_ns, dur_ns, tid, args, flow in events:
            ev = {"name": name, "ph": ph, "pid": 0, "tid": tid_of(tid),
                  "ts": t0_ns / 1e3}
            if ph == "X":
                ev["dur"] = dur_ns / 1e3
            elif ph in ("s", "f"):
                # flow arrow: id pairs the start with its finish; "f"
                # binds to the ENCLOSING slice (bp:"e") so the arrow
                # lands on the dispatch span, not the next slice
                ev["id"] = flow
                if ph == "f":
                    ev["bp"] = "e"
            else:
                ev["s"] = "t"
            if cat:
                ev["cat"] = cat
            if args:
                ev["args"] = args
            out.append(ev)
        meta = [{"name": "process_name", "ph": "M", "pid": 0,
                 "args": {"name": process_name}}]
        for tid, small in sorted(tid_map.items(), key=lambda kv: kv[1]):
            label = tid if isinstance(tid, str) else f"host-{small}"
            meta.append({"name": "thread_name", "ph": "M", "pid": 0,
                         "tid": small, "args": {"name": label}})
        return {"traceEvents": meta + out, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self._dropped,
                              "span_count": len(out)}}

    def dump(self, path: str, process_name: str = "bigdl_tpu") -> str:
        """Write the Chrome-trace JSON to ``path`` and return it."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(process_name), f)
        return path
