"""Core parameterized layers.

Reference: the ~210 per-file layers at ``DL/nn/`` top level.  Kernels that
the reference routes to MKL JNI (gemm in ``Linear.scala:92-157``, im2col+gemm
in ``SpatialConvolution.scala:612-646``) are a single jnp/lax op here — XLA
lowers them to the MXU, which is the whole point of the TPU-native design.

Conventions (TPU-first, documented divergences from the reference):
- dims are 0-based with batch at axis 0 (reference/Torch is 1-based);
- conv layout defaults to NCHW for API parity but NHWC is supported via
  ``format=`` and is preferred on TPU;
- class targets are 0-based (reference/Torch 1-based).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform


class Linear(Module):
    """Affine layer y = xW^T + b (reference ``DL/nn/Linear.scala:44``;
    its MKL gemm call sites `:92,107,125-157` become one jnp.dot → MXU)."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 bias_init: Optional[InitializationMethod] = None,
                 shard: Optional[str] = None,
                 w_regularizer=None, b_regularizer=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()
        self.bias_init = bias_init or RandomUniform()
        # per-layer penalties (reference wRegularizer/bRegularizer ctor
        # args; collected by nn.regularizers.regularization_loss)
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        # tensor parallelism: "column" (split output dim) / "row" (split
        # input dim) / None — see parallel/tensor_parallel.py
        self.shard = shard

    def param_specs(self):
        if self.shard is None:
            return None
        from bigdl_tpu.parallel.tensor_parallel import (
            column_parallel_linear_specs, row_parallel_linear_specs)
        if self.shard == "column":
            return column_parallel_linear_specs(self.with_bias)
        if self.shard == "row":
            return row_parallel_linear_specs(self.with_bias)
        raise ValueError(f"unknown shard mode {self.shard!r}")

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        fan_in, fan_out = self.input_size, self.output_size
        params = {"weight": self.weight_init.init(
            k_w, (self.output_size, self.input_size), fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init.init(k_b, (self.output_size,),
                                                 fan_in, fan_out)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        y = jnp.dot(input, params["weight"].T)
        if self.with_bias:
            y = y + params["bias"]
        return y, state


def _conv_dims(fmt: str):
    if fmt == "NCHW":
        return ("NCHW", "OIHW", "NCHW")
    elif fmt == "NHWC":
        return ("NHWC", "HWIO", "NHWC")
    raise ValueError(f"unknown format {fmt}")


class SpatialConvolution(Module):
    """2-D convolution (reference ``DL/nn/SpatialConvolution.scala:54``:
    im2col + MKL gemm with per-sample threading — here one
    ``lax.conv_general_dilated``, tiled onto the MXU by XLA).

    Weight shape OIHW: (n_output, n_input/group, kh, kw)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 n_group: int = 1, with_bias: bool = True,
                 dilation_w: int = 1, dilation_h: int = 1,
                 format: str = "NCHW",
                 weight_init: Optional[InitializationMethod] = None,
                 bias_init: Optional[InitializationMethod] = None,
                 w_regularizer=None, b_regularizer=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.n_group = n_group
        self.with_bias = with_bias
        self.dilation = (dilation_h, dilation_w)
        self.format = format
        self.weight_init = weight_init or RandomUniform()
        self.bias_init = bias_init or RandomUniform()

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input_plane // self.n_group * kh * kw
        fan_out = self.n_output_plane // self.n_group * kh * kw
        w_shape = (self.n_output_plane, self.n_input_plane // self.n_group, kh, kw)
        params = {"weight": self.weight_init.init(k_w, w_shape, fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init.init(k_b, (self.n_output_plane,),
                                                 fan_in, fan_out)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        w = params["weight"]
        if self.format == "NHWC":
            w = jnp.transpose(w, (2, 3, 1, 0))  # OIHW -> HWIO
        # SAME_LOWER not needed: reference pad=-1 means "same"; handle it
        ph, pw = self.pad
        if ph == -1 or pw == -1:
            padding = "SAME"
        else:
            padding = ((ph, ph), (pw, pw))
        y = lax.conv_general_dilated(
            input, w,
            window_strides=self.stride,
            padding=padding,
            rhs_dilation=self.dilation,
            dimension_numbers=_conv_dims(self.format),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            b = params["bias"]
            y = y + (b[None, :, None, None] if self.format == "NCHW"
                     else b[None, None, None, :])
        # offloadable-residual tag: a no-op normally, but lets a Remat
        # policy (save_only_these_names("conv_out")) keep conv outputs
        # while recomputing the cheap BN/ReLU tails in backward —
        # recomputing a conv would re-read its input from HBM, which is
        # exactly the traffic remat is trying to save
        y = checkpoint_name(y, "conv_out")
        return y, state


class SpatialFullConvolution(Module):
    """Transposed 2-D convolution (reference ``SpatialFullConvolution.scala``;
    deconvolution for FCN/segmentation heads)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 adj_w: int = 0, adj_h: int = 0,
                 with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 bias_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.adj = (adj_h, adj_w)
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()
        self.bias_init = bias_init or RandomUniform()

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input_plane * kh * kw
        fan_out = self.n_output_plane * kh * kw
        w_shape = (self.n_input_plane, self.n_output_plane, kh, kw)  # IOHW
        params = {"weight": self.weight_init.init(k_w, w_shape, fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init.init(k_b, (self.n_output_plane,),
                                                 fan_in, fan_out)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        ah, aw = self.adj
        # transposed conv as a fractionally-strided direct conv: dilate the
        # input by the stride, convolve with the spatially-flipped kernel
        # (IOHW -> OIHW with O = n_output_plane).
        # output size = (in-1)*stride - 2*pad + kernel + adj
        w = jnp.transpose(jnp.flip(params["weight"], axis=(2, 3)), (1, 0, 2, 3))
        y = lax.conv_general_dilated(
            input, w,
            window_strides=(1, 1),
            padding=((kh - 1 - ph, kh - 1 - ph + ah),
                     (kw - 1 - pw, kw - 1 - pw + aw)),
            lhs_dilation=(sh, sw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.with_bias:
            y = y + params["bias"][None, :, None, None]
        return y, state


class _Pool2D(Module):
    def __init__(self, kernel_w: int, kernel_h: int,
                 stride_w: Optional[int] = None, stride_h: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0,
                 ceil_mode: bool = False, format: str = "NCHW",
                 name: Optional[str] = None):
        super().__init__(name)
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h or kernel_h, stride_w or kernel_w)
        self.pad = (pad_h, pad_w)
        self.ceil_mode = ceil_mode
        self.format = format

    def _window(self, input_shape):
        spatial = (input_shape[2], input_shape[3]) if self.format == "NCHW" \
            else (input_shape[1], input_shape[2])
        hw_pads = tuple(
            (self.pad[i], self.pad[i] + self._extra(i, spatial[i]))
            for i in (0, 1))
        if self.format == "NCHW":
            dims = (1, 1) + self.kernel
            strides = (1, 1) + self.stride
            pads = ((0, 0), (0, 0)) + hw_pads
        else:
            dims = (1,) + self.kernel + (1,)
            strides = (1,) + self.stride + (1,)
            pads = ((0, 0),) + hw_pads + ((0, 0),)
        return dims, strides, pads

    def _extra(self, i, size):
        """Trailing pad beyond ``pad[i]`` implementing Torch/BigDL ceil mode:
        keep the last partial window, but drop a window whose *start* lies
        beyond input+pad ((out-1)*stride >= size+pad — reference
        SpatialMaxPooling ceil/floor modes)."""
        k, s, p = self.kernel[i], self.stride[i], self.pad[i]
        if self.ceil_mode:
            out = -(-(size + 2 * p - k) // s) + 1  # ceil div
            if (out - 1) * s >= size + p:
                out -= 1
        else:
            out = (size + 2 * p - k) // s + 1
        return max(0, (out - 1) * s + k - size - 2 * p)


def _phase_max_1d(x, axis, k, s, pad_lo, pad_hi):
    """Max-pool one spatial axis via phase decomposition: reshape the
    axis into (groups, s) and take k UNSTRIDED slice-maxima instead of
    a ``lax.reduce_window``.

    Why: on TPU, XLA lowers reduce_window/select-and-scatter to window
    loops that run far below HBM bandwidth (measured ~8 ms of waste per
    Inception-v1 step at batch 256 vs the same model with pooling
    ablated); plain slices + elementwise max fuse into loop fusions
    that run at bandwidth.  The ``where(cand > best)`` chain makes ties
    keep the EARLIER window position along THIS axis, so autodiff
    routes gradient to a single maximum — but because the 2-D pool is
    computed separably (H pass then W pass), the tie ORDER across a 2-D
    window is column-major, not the reference/select-and-scatter
    row-major scan: on exact ties (e.g. post-ReLU zeros) the gradient
    lands on a different — still maximal — element.
    """
    size = x.shape[axis]
    out = (size + pad_lo + pad_hi - k) // s + 1
    qmax = (k - 1) // s
    groups = out + qmax  # slices index groups [d//s, d//s + out)
    full = groups * s

    pad_cfg = [(0, 0, 0)] * x.ndim
    pad_cfg[axis] = (pad_lo, full - size - pad_lo, 0)
    xp = lax.pad(x, jnp.asarray(-jnp.inf, x.dtype), pad_cfg)
    v = xp.reshape(xp.shape[:axis] + (groups, s) + xp.shape[axis + 1:])

    ix_pre = (slice(None),) * axis
    best = None
    for d in range(k):
        q, r = divmod(d, s)
        cand = v[ix_pre + (slice(q, q + out), r)]
        best = cand if best is None else jnp.where(cand > best, cand, best)
    return best


class SpatialMaxPooling(_Pool2D):
    """Max pooling (reference ``SpatialMaxPooling.scala``).

    ``impl="reduce_window"`` (default) is the direct XLA window op —
    measured FASTEST end-to-end on v5e despite its select-and-scatter
    backward running ~8.6 ms/step below bandwidth on Inception-v1
    (batch 256): every alternative formulation tried loses more to
    materialisation/layout copies than S&S wastes (r4 experiment log):
    - ``impl="phase"`` (separable slice-max via :func:`_phase_max_1d`):
      intermediates hit HBM, 37.3→67.8 GB/step;
    - ``impl="pallas_bwd"`` (first-match pallas kernel,
      :mod:`bigdl_tpu.ops.pallas_pool`): correct, VMEM-resident, but
      pallas only accepts default layouts while XLA lays these
      activations out batch-minor — the transposes around every call
      cost 3× more than S&S (37.3→80.4 GB/step);
    - a hand-written custom-vjp in plain XLA ops: XLA materialises the
      k² first-match/scatter chains, 37.3→95.9 GB/step.
    The pallas kernel remains available (opt-in) for layout-friendly
    contexts and as the reference first-match implementation."""

    def __init__(self, *args, impl: str = "reduce_window", **kw):
        super().__init__(*args, **kw)
        if impl not in ("reduce_window", "phase", "pallas_bwd"):
            raise ValueError(f"unknown SpatialMaxPooling impl {impl!r}; "
                             "use 'reduce_window', 'phase' or 'pallas_bwd'")
        self.impl = impl

    def apply(self, params, state, input, *, training=False, rng=None):
        dims, strides, pads = self._window(input.shape)
        if self.impl == "phase":
            h_ax, w_ax = (2, 3) if self.format == "NCHW" else (1, 2)
            (kh, kw), (sh, sw) = self.kernel, self.stride
            y = _phase_max_1d(input, h_ax, kh, sh, *pads[h_ax])
            y = _phase_max_1d(y, w_ax, kw, sw, *pads[w_ax])
            return y, state
        if self.impl == "pallas_bwd":
            if self.format != "NHWC" or input.ndim != 4:
                raise ValueError(
                    "impl='pallas_bwd' requires 4-D NHWC input "
                    f"(got format={self.format}, ndim={input.ndim})")
            from bigdl_tpu.ops.pallas_pool import \
                maxpool_nhwc_with_pallas_bwd
            y = maxpool_nhwc_with_pallas_bwd(input, dims, strides, pads)
            return y, state
        y = lax.reduce_window(input, -jnp.inf, lax.max, dims, strides, pads)
        return y, state


class SpatialAveragePooling(_Pool2D):
    """Average pooling (reference ``SpatialAveragePooling.scala``;
    ``count_include_pad`` matches its countIncludePad=true default)."""

    def __init__(self, *args, count_include_pad: bool = True, **kw):
        super().__init__(*args, **kw)
        self.count_include_pad = count_include_pad

    def apply(self, params, state, input, *, training=False, rng=None):
        dims, strides, pads = self._window(input.shape)
        summed = lax.reduce_window(input, 0.0, lax.add, dims, strides, pads)
        if self.count_include_pad:
            y = summed / (self.kernel[0] * self.kernel[1])
        else:
            ones = jnp.ones_like(input)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
            y = summed / jnp.maximum(counts, 1.0)
        return y, state


class SpatialBatchNormalization(Module):
    """BatchNorm over NCHW (reference ``SpatialBatchNormalization.scala``;
    running stats use torch momentum semantics:
    running = (1-momentum)*running + momentum*batch, momentum default 0.1)."""

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, format: str = "NCHW",
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.format = format
        self._axes = (0, 2, 3) if format == "NCHW" else (0, 1, 2)

    def init(self, rng):
        params = {}
        if self.affine:
            params = {"weight": jnp.ones((self.n_output,), jnp.float32),
                      "bias": jnp.zeros((self.n_output,), jnp.float32)}
        state = {"running_mean": jnp.zeros((self.n_output,), jnp.float32),
                 "running_var": jnp.ones((self.n_output,), jnp.float32)}
        return params, state

    def _reshape(self, v, ndim):
        shape = [1] * ndim
        shape[1 if self.format == "NCHW" else -1] = self.n_output
        return v.reshape(shape)

    def apply(self, params, state, input, *, training=False, rng=None):
        ndim = input.ndim
        axes = self._axes if ndim == 4 else (0,)
        if training:
            # one-pass stats: E[x²]-E[x]² lets XLA fuse both reductions into
            # a single sweep over the (large) activation — jnp.var's
            # two-pass form reads it twice.  Accumulate in f32: bf16
            # squares lose too many bits for the cancellation.
            # jax.checkpoint: without it XLA saves the f32 UPCAST of the
            # bf16 activation as a backward residual (an 822 MB top-level
            # f32 copy for ResNet-50's stem at batch 256, seen in the
            # r4 HLO audit); rematerializing the cast trades one cheap
            # convert for ~2 GB/step of HBM traffic.
            def _stats(xin):
                xf = xin.astype(jnp.float32)
                mean = jnp.mean(xf, axis=axes)
                var = jnp.mean(jnp.square(xf), axis=axes) \
                    - jnp.square(mean)
                return mean, jnp.maximum(var, 0.0)

            mean, var = jax.checkpoint(_stats)(input)
            n = input.size / self.n_output
            unbiased = var * n / max(n - 1, 1)
            m = self.momentum
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        # fold (mean, inv, gamma, beta) into one scale+shift so the big
        # activation is touched exactly once, in its own (bf16) dtype
        scale, shift = inv, -mean * inv
        if self.affine:
            scale = scale * params["weight"]
            shift = shift * params["weight"] + params["bias"]
        y = input * self._reshape(scale.astype(input.dtype), ndim) \
            + self._reshape(shift.astype(input.dtype), ndim)
        return y, new_state


class BatchNormalization(SpatialBatchNormalization):
    """1-D BatchNorm over (N, C) (reference ``BatchNormalization.scala``)."""
    pass


class Dropout(Module):
    """Inverted dropout (reference ``Dropout.scala``: scales by 1/(1-p) in
    train, identity in eval)."""

    def __init__(self, init_p: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.p = init_p

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return input, state
        if rng is None:
            raise ValueError("Dropout in training mode needs an rng")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, input.shape)
        return jnp.where(mask, input / keep, 0.0), state


class LookupTable(Module):
    """Embedding lookup (reference ``LookupTable.scala``).  Indices are
    0-based here (reference is 1-based Torch).  ``padding_value`` rows are
    zeroed like the reference's paddingValue."""

    def __init__(self, n_index: int, n_output: int,
                 padding_value: Optional[int] = None,
                 max_norm: Optional[float] = None,
                 weight_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        from bigdl_tpu.nn.initialization import RandomNormal
        self.weight_init = weight_init or RandomNormal(0.0, 1.0)

    def init(self, rng):
        w = self.weight_init.init(rng, (self.n_index, self.n_output),
                                  self.n_index, self.n_output)
        if self.padding_value is not None:
            w = w.at[self.padding_value].set(0.0)
        return {"weight": w}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        w = params["weight"]
        if self.max_norm is not None:
            norms = jnp.linalg.norm(w, axis=1, keepdims=True)
            w = w * jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-7))
        idx = input.astype(jnp.int32)
        return jnp.take(w, idx, axis=0), state


class SpatialCrossMapLRN(Module):
    """Local response normalization across channels
    (reference ``SpatialCrossMapLRN.scala``; AlexNet/Inception-v1 era)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, format: str = "NCHW",
                 name: Optional[str] = None):
        super().__init__(name)
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.format = format

    def apply(self, params, state, input, *, training=False, rng=None):
        # sum x^2 over a window of `size` channels (channel axis by format)
        sq = input * input
        half = (self.size - 1) // 2
        extra = self.size - 1 - half
        dims = [1, 1, 1, 1]
        pads = [(0, 0)] * 4
        c_axis = 1 if self.format == "NCHW" else 3
        dims[c_axis] = self.size
        pads[c_axis] = (half, extra)
        acc = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=tuple(dims),
            window_strides=(1, 1, 1, 1),
            padding=tuple(pads))
        denom = jnp.power(self.k + (self.alpha / self.size) * acc, self.beta)
        return input / denom, state


class Normalize(Module):
    """Lp-normalize along dim 1 (reference ``Normalize.scala``)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10,
                 name: Optional[str] = None):
        super().__init__(name)
        self.p, self.eps = p, eps

    def apply(self, params, state, input, *, training=False, rng=None):
        if self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(input * input, axis=1, keepdims=True))
        else:
            norm = jnp.power(jnp.sum(jnp.power(jnp.abs(input), self.p),
                                     axis=1, keepdims=True), 1.0 / self.p)
        return input / (norm + self.eps), state


class NormalizeScale(Module):
    """Lp-normalize across channels, then a LEARNABLE per-channel scale
    (reference ``NormalizeScale.scala`` — SSD's conv4_3 L2Norm layer;
    ``scale`` is the constant init of the weight, 20 in the SSD recipe).

    ``size`` is the broadcastable weight shape, e.g. ``(1, 512, 1, 1)``
    for NCHW feature maps (matching the reference's CMul size)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10,
                 scale: float = 1.0, size: Sequence[int] = (1,),
                 name: Optional[str] = None):
        super().__init__(name)
        self.p, self.eps, self.scale = p, eps, scale
        self.size = tuple(size)

    def init(self, rng):
        return {"weight": jnp.full(self.size, self.scale, jnp.float32)}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        if self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(input * input, axis=1, keepdims=True))
        else:
            norm = jnp.power(jnp.sum(jnp.power(jnp.abs(input), self.p),
                                     axis=1, keepdims=True), 1.0 / self.p)
        return (input / (norm + self.eps)) * params["weight"], state


class CMul(Module):
    """Learnable per-element scale, broadcast over batch
    (reference ``CMul.scala``)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def init(self, rng):
        fan = int(jnp.prod(jnp.array(self.size)))
        w = RandomUniform().init(rng, self.size, fan, fan)
        return {"weight": w}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        return input * params["weight"], state


class CAdd(Module):
    """Learnable per-element bias (reference ``CAdd.scala``)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def init(self, rng):
        fan = int(jnp.prod(jnp.array(self.size)))
        b = RandomUniform().init(rng, self.size, fan, fan)
        return {"bias": b}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        return input + params["bias"], state


class TemporalConvolution(Module):
    """1-D convolution over (N, T, C_in) (reference
    ``TemporalConvolution.scala``)."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1,
                 weight_init: Optional[InitializationMethod] = None,
                 bias_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.weight_init = weight_init or RandomUniform()
        self.bias_init = bias_init or RandomUniform()

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.input_frame_size * self.kernel_w
        fan_out = self.output_frame_size * self.kernel_w
        w = self.weight_init.init(
            k_w, (self.output_frame_size, self.input_frame_size, self.kernel_w),
            fan_in, fan_out)
        b = self.bias_init.init(k_b, (self.output_frame_size,), fan_in, fan_out)
        return {"weight": w, "bias": b}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        # (N, T, C) -> conv via NWC layout
        y = lax.conv_general_dilated(
            input, jnp.transpose(params["weight"], (2, 1, 0)),  # OIW->WIO
            window_strides=(self.stride_w,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        return y + params["bias"], state
