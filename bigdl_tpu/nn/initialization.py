"""Weight initialization methods (reference ``DL/nn/InitializationMethod.scala``).

Each method is ``init(rng, shape, fan_in, fan_out) -> array``.  Layers are
"Initializable": they take ``weight_init`` / ``bias_init`` kwargs mirroring
the reference's ``setInitMethod(weightInitMethod, biasInitMethod)``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


class InitializationMethod:
    def init(self, rng, shape, fan_in, fan_out):
        raise NotImplementedError


class Zeros(InitializationMethod):
    def init(self, rng, shape, fan_in, fan_out):
        return jnp.zeros(shape, jnp.float32)


class Ones(InitializationMethod):
    def init(self, rng, shape, fan_in, fan_out):
        return jnp.ones(shape, jnp.float32)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value: float):
        self.value = value

    def init(self, rng, shape, fan_in, fan_out):
        return jnp.full(shape, self.value, jnp.float32)


class Xavier(InitializationMethod):
    """Glorot uniform: U(-a, a), a = sqrt(6/(fan_in+fan_out))
    (reference ``InitializationMethod.scala`` Xavier)."""

    def init(self, rng, shape, fan_in, fan_out):
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, jnp.float32, -a, a)


class MsraFiller(InitializationMethod):
    """Kaiming/He normal: N(0, sqrt(2/fan)) (reference MsraFiller;
    ``varianceNormAverage=false`` → fan_in)."""

    def __init__(self, variance_norm_average: bool = False):
        self.variance_norm_average = variance_norm_average

    def init(self, rng, shape, fan_in, fan_out):
        fan = (fan_in + fan_out) / 2.0 if self.variance_norm_average else fan_in
        std = math.sqrt(2.0 / fan)
        return std * jax.random.normal(rng, shape, jnp.float32)


class RandomUniform(InitializationMethod):
    """U(lower, upper); with no bounds, the Torch default U(-1/sqrt(fan_in),
    1/sqrt(fan_in)) used by Linear/SpatialConvolution in the reference."""

    def __init__(self, lower: float | None = None, upper: float | None = None):
        self.lower, self.upper = lower, upper

    def init(self, rng, shape, fan_in, fan_out):
        if self.lower is None:
            b = 1.0 / math.sqrt(max(fan_in, 1))
            lo, hi = -b, b
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(rng, shape, jnp.float32, lo, hi)


class RandomNormal(InitializationMethod):
    def __init__(self, mean: float = 0.0, stdv: float = 1.0):
        self.mean, self.stdv = mean, stdv

    def init(self, rng, shape, fan_in, fan_out):
        return self.mean + self.stdv * jax.random.normal(rng, shape, jnp.float32)


class BilinearFiller(InitializationMethod):
    """Bilinear-upsampling kernel init for full (transposed) convolutions
    (reference BilinearFiller; weight shape (..., kh, kw))."""

    def init(self, rng, shape, fan_in, fan_out):
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = math.ceil(kh / 2.0), math.ceil(kw / 2.0)
        c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        ys = jnp.arange(kh)[:, None]
        xs = jnp.arange(kw)[None, :]
        filt = (1 - jnp.abs(ys / f_h - c_h)) * (1 - jnp.abs(xs / f_w - c_w))
        return jnp.broadcast_to(filt, shape).astype(jnp.float32)
