"""Module system — the TPU-native replacement for BigDL's nn contract.

Reference: ``DL/nn/abstractnn/AbstractModule.scala:58`` defines a *mutable*
contract — ``updateOutput`` writes ``this.output``, ``updateGradInput`` /
``accGradParameters`` hand-write every backward pass, and layers carry their
weights as fields.

That design cannot live under XLA: everything inside ``jit`` must be a pure
function of its inputs.  So the contract here is *functional*:

- a :class:`Module` is an immutable **descriptor** (hyper-parameters only);
- ``init(rng)`` returns ``(params, state)`` pytrees — ``params`` is the
  trainable pytree (reference: ``parameters()`` weight arrays,
  ``AbstractModule.scala:337``), ``state`` the non-trainable running
  statistics (BatchNorm means/vars);
- ``apply(params, state, input, training=..., rng=...)`` returns
  ``(output, new_state)`` and is pure → jit/grad/vmap/shard_map-compatible;
- **there is no hand-written backward anywhere** — ``jax.grad`` of the loss
  w.r.t. ``params`` replaces ``updateGradInput`` + ``accGradParameters``.

For API parity with BigDL scripts (``model.forward(x)``; gradient checks),
Module also offers a thin *eager* convenience layer that stores
``(params, state)`` on the object and calls the pure ``apply`` under the
hood; training loops never use it.

``Activity`` (reference ``Activity.scala:33``: Tensor | Table) maps to
"array | tuple/list/dict of arrays" — i.e. any pytree.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


def _as_rng(seed_or_rng) -> jax.Array:
    if isinstance(seed_or_rng, int):
        return jax.random.PRNGKey(seed_or_rng)
    return seed_or_rng


class Module:
    """Base class of all layers.  See module docstring for the contract."""

    def __init__(self, name: Optional[str] = None):
        self.name = name if name is not None else type(self).__name__
        # eager-convenience slots (not part of the pure contract)
        self._params: Any = None
        self._state: Any = None
        self._grads: Any = None
        self.training: bool = True

    # ---------------------------------------------------------------- pure
    def init(self, rng: jax.Array):
        """Return ``(params, state)`` pytrees. Stateless layers return ({}, {})."""
        return {}, {}

    def apply(self, params, state, input, *, training: bool = False,
              rng: Optional[jax.Array] = None):
        """Pure forward: return ``(output, new_state)``."""
        raise NotImplementedError(type(self).__name__)

    # ------------------------------------------------------- eager parity
    def initialize(self, rng=0) -> "Module":
        """Materialize params on the object (eager/demo use only)."""
        self._params, self._state = self.init(_as_rng(rng))
        self._grads = jax.tree_util.tree_map(jnp.zeros_like, self._params)
        return self

    def _ensure_init(self):
        if self._params is None:
            self.initialize()

    def forward(self, input, rng: Optional[jax.Array] = None):
        """Eager forward (reference: ``AbstractModule.forward``, `:254`)."""
        self._ensure_init()
        out, self._state = self.apply(self._params, self._state, input,
                                      training=self.training, rng=rng)
        self.output = out
        return out

    def __call__(self, input, rng: Optional[jax.Array] = None):
        # functional-graph syntax: calling a module on Node(s) builds a DAG
        # edge instead of running eagerly (see nn/graph.py)
        from bigdl_tpu.nn.graph import Node
        if isinstance(input, Node) or (
                isinstance(input, (list, tuple)) and input
                and all(isinstance(e, Node) for e in input)):
            prev = [input] if isinstance(input, Node) else list(input)
            return Node(self, prev)
        return self.forward(input, rng=rng)

    def backward(self, input, grad_output, rng: Optional[jax.Array] = None):
        """Eager backward via ``jax.vjp`` — replaces the reference's
        hand-written ``updateGradInput``+``accGradParameters``
        (``AbstractModule.scala:280-287``).  Accumulates into ``self._grads``
        (reference semantics: accGradParameters *accumulates*) and returns
        grad_input."""
        self._ensure_init()

        def fwd(params, x):
            y, _ = self.apply(params, self._state, x,
                              training=self.training, rng=rng)
            return y

        _, vjp = jax.vjp(fwd, self._params, input)
        d_params, d_input = vjp(grad_output)
        self._grads = jax.tree_util.tree_map(jnp.add, self._grads, d_params)
        self.grad_input = d_input
        return d_input

    def zero_grad_parameters(self):
        if self._grads is not None:
            self._grads = jax.tree_util.tree_map(jnp.zeros_like, self._grads)

    # --------------------------------------------------------- inference
    def predict(self, data, batch_size: int = 128):
        """Batched inference (reference ``AbstractModule.predict``; see
        optim/predictor.py)."""
        from bigdl_tpu.optim.predictor import Predictor
        return Predictor(self, batch_size=batch_size).predict(data)

    def predict_class(self, data, batch_size: int = 128):
        from bigdl_tpu.optim.predictor import Predictor
        return Predictor(self, batch_size=batch_size).predict_class(data)

    def evaluate_on(self, dataset, methods):
        """Metric evaluation (reference ``AbstractModule.evaluate(...)``
        entry points, `:845-895`)."""
        from bigdl_tpu.optim.predictor import Evaluator
        return Evaluator(self).evaluate(dataset, methods)

    # ------------------------------------------------------------- modes
    def evaluate(self) -> "Module":
        """Switch eager mode to inference (reference ``:429-445``)."""
        self.training = False
        return self

    def training_mode(self) -> "Module":
        self.training = True
        return self

    # -------------------------------------------------------- parameters
    def parameters(self):
        """Eager ``(params, grads)`` pair (reference ``parameters()``, `:337`)."""
        self._ensure_init()
        return self._params, self._grads

    def get_parameters(self):
        """Flat-vector view of params + an unravel fn.

        The reference compacts all weights into one flat Tensor
        (``getParameters()``) because its AllReduce/checkpoint layers assume
        a flat view; here the pytree is primary and the flat view is derived.
        """
        self._ensure_init()
        flat, unravel = ravel_pytree(self._params)
        return flat, unravel

    def set_parameters(self, params):
        self._params = params
        return self

    def _set_import_params(self, params=None, state=None) -> "Module":
        """Importer helper: overwrite freshly-initialized params/state
        entries with (numpy) arrays, keeping pytree structure and shapes
        (``None`` values and missing keys are left at their init)."""
        self._ensure_init()

        def merge(dst, src):
            for k, v in (src or {}).items():
                if v is None:
                    continue
                if isinstance(v, dict):
                    merge(dst[k], v)
                else:
                    dst[k] = jnp.asarray(np.asarray(v), jnp.float32) \
                        .reshape(dst[k].shape)

        merge(self._params, params)
        merge(self._state, state)
        self._grads = jax.tree_util.tree_map(jnp.zeros_like, self._params)
        return self

    # ---------------------------------------------------- spec traversal
    def spec_children(self):
        """How sharding-spec builders traverse this module
        (``parallel.tensor_parallel.build_param_specs``):

        - ``None`` (default): leaf — params replicated unless the module
          overrides ``param_specs()``;
        - a single ``Module``: this wrapper delegates ``init`` to that
          child (params structures identical);
        - a dict ``{param_key: Module}``: params nest children under
          those keys.
        """
        return None

    # -------------------------------------------------------------- misc
    def set_name(self, name: str) -> "Module":
        self.name = name
        return self

    def get_name(self) -> str:
        return self.name

    def __repr__(self):
        return f"{type(self).__name__}[{self.name}]"


class Container(Module):
    """Composite module holding children (reference ``Container.scala:40``).

    Child params/state are stored as dicts keyed by ``"{index}"`` so the
    pytree structure is stable under jit and independent of layer names
    (names may repeat)."""

    def __init__(self, *modules: Module, name: Optional[str] = None):
        super().__init__(name)
        self.modules: list[Module] = []
        for m in modules:
            self.add(m)

    def add(self, module: Module) -> "Container":
        self.modules.append(module)
        return self

    def spec_children(self):
        return {str(i): m for i, m in enumerate(self.modules)}

    def __len__(self):
        return len(self.modules)

    def __getitem__(self, i) -> Module:
        return self.modules[i]

    def init(self, rng):
        params, state = {}, {}
        for i, m in enumerate(self.modules):
            rng, sub = jax.random.split(rng)
            p, s = m.init(sub)
            params[str(i)] = p
            state[str(i)] = s
        return params, state

    def _split_rng(self, rng, n):
        if rng is None:
            return [None] * n
        return list(jax.random.split(rng, n))


class Sequential(Container):
    """Feed children in order (reference ``Sequential.scala:31``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input
        new_state = {}
        rngs = self._split_rng(rng, len(self.modules))
        for i, m in enumerate(self.modules):
            out, s = m.apply(params[str(i)], state[str(i)], out,
                             training=training, rng=rngs[i])
            new_state[str(i)] = s
        return out, new_state


class ConcatTable(Container):
    """Apply every child to the same input, return a tuple
    (reference ``ConcatTable``: Tensor → Table)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], {}
        rngs = self._split_rng(rng, len(self.modules))
        for i, m in enumerate(self.modules):
            o, s = m.apply(params[str(i)], state[str(i)], input,
                           training=training, rng=rngs[i])
            outs.append(o)
            new_state[str(i)] = s
        return tuple(outs), new_state


class ParallelTable(Container):
    """Apply the i-th child to the i-th input element (reference
    ``ParallelTable``: Table → Table)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], {}
        rngs = self._split_rng(rng, len(self.modules))
        for i, m in enumerate(self.modules):
            o, s = m.apply(params[str(i)], state[str(i)], input[i],
                           training=training, rng=rngs[i])
            outs.append(o)
            new_state[str(i)] = s
        return tuple(outs), new_state


class Concat(Container):
    """Apply every child to the input and concatenate outputs along ``dim``
    (reference ``Concat.scala``; dim counts the batch axis, default 1 =
    feature/channel axis, matching BigDL's 1-based dimension minus one —
    here dims are 0-based with batch at 0, so channel concat is dim=1)."""

    def __init__(self, dim: int = 1, name: Optional[str] = None):
        super().__init__(name=name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], {}
        rngs = self._split_rng(rng, len(self.modules))
        for i, m in enumerate(self.modules):
            o, s = m.apply(params[str(i)], state[str(i)], input,
                           training=training, rng=rngs[i])
            outs.append(o)
            new_state[str(i)] = s
        return jnp.concatenate(outs, axis=self.dim), new_state


class Identity(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state


class Echo(Module):
    """Debug layer: prints shape at trace time (reference ``Echo.scala``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        shapes = jax.tree_util.tree_map(lambda x: x.shape, input)
        print(f"[Echo {self.name}] {shapes}")
        return input, state


class Lambda(Module):
    """Wrap a pure function as a stateless layer (no reference analog;
    replaces dozens of trivial tensor-manip layers in user code)."""

    def __init__(self, fn: Callable, name: Optional[str] = None):
        super().__init__(name)
        self.fn = fn

    def apply(self, params, state, input, *, training=False, rng=None):
        return self.fn(input), state


class Remat(Module):
    """Rematerialization wrapper: the child's activations are NOT saved
    for backward — they are recomputed (``jax.checkpoint``).  Trades
    FLOPs for HBM traffic/footprint; no reference analog (the reference
    stores every ``output`` field by construction).  Use on repeated
    blocks (residual blocks, transformer layers) when memory- or
    bandwidth-bound."""

    def __init__(self, inner: Module, policy=None,
                 name: Optional[str] = None):
        super().__init__(name or f"Remat[{inner.name}]")
        self.inner = inner
        self.policy = policy

    def spec_children(self):
        return self.inner

    def init(self, rng):
        return self.inner.init(rng)

    def apply(self, params, state, input, *, training=False, rng=None):
        def fn(p, s, x, r):
            return self.inner.apply(p, s, x, training=training, rng=r)
        return jax.checkpoint(fn, policy=self.policy)(params, state,
                                                      input, rng)
