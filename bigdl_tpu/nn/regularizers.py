"""Per-layer weight regularizers.

Reference: ``DL/optim/Regularizer.scala`` — ``L1L2Regularizer(l1, l2)``
adds ``l1*sign(w) + l2*w`` to ``gradWeight`` inside each layer's
``accGradParameters``; layers take ``wRegularizer``/``bRegularizer``
constructor args.

TPU redesign: there is no hand-written ``accGradParameters`` to hook —
the equivalent penalty enters the LOSS (``jax.grad`` then produces
exactly the reference's gradient contribution): ``l1*|w|_1 +
(l2/2)*|w|_2^2``.  Layers carry ``w_regularizer``/``b_regularizer``
attributes; :func:`regularization_loss` walks a module's
``spec_children`` tree pairing each module with its params subtree and
sums every attached penalty, and both optimizers add it to the
criterion loss.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


class Regularizer:
    def penalty(self, w):
        raise NotImplementedError


class L1L2Regularizer(Regularizer):
    """``l1*|w|_1 + (l2/2)*|w|_2^2`` — the gradient is the reference's
    ``l1*sign(w) + l2*w`` (``Regularizer.scala`` accRegularization)."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        self.l1 = float(l1)
        self.l2 = float(l2)

    def penalty(self, w):
        out = 0.0
        if self.l1:
            out = out + self.l1 * jnp.sum(jnp.abs(w))
        if self.l2:
            out = out + 0.5 * self.l2 * jnp.sum(w * w)
        return out

    def __repr__(self):
        return f"{type(self).__name__}(l1={self.l1}, l2={self.l2})"


class L1Regularizer(L1L2Regularizer):
    def __init__(self, l1: float):
        super().__init__(l1=l1, l2=0.0)


class L2Regularizer(L1L2Regularizer):
    def __init__(self, l2: float):
        super().__init__(l1=0.0, l2=l2)


def regularization_loss(module, params):
    """Sum every layer's attached ``w_regularizer``/``b_regularizer``
    penalty over the matching params subtree.  Returns 0.0 when no layer
    carries a regularizer (the common case — jit folds it away)."""
    total = 0.0

    def walk(mod, p):
        nonlocal total
        wr = getattr(mod, "w_regularizer", None)
        br = getattr(mod, "b_regularizer", None)
        if wr is not None and isinstance(p, dict) and "weight" in p:
            total = total + wr.penalty(p["weight"])
        if br is not None and isinstance(p, dict) and "bias" in p:
            total = total + br.penalty(p["bias"])
        children = mod.spec_children()
        if children is None:
            return
        if isinstance(children, dict):
            for k, c in children.items():
                walk(c, p.get(k, {}) if isinstance(p, dict) else {})
        else:
            walk(children, p)

    walk(module, params)
    return total


def has_regularizers(module) -> bool:
    found = False

    def walk(mod):
        nonlocal found
        if getattr(mod, "w_regularizer", None) is not None \
                or getattr(mod, "b_regularizer", None) is not None:
            found = True
            return
        children = mod.spec_children()
        if isinstance(children, dict):
            for c in children.values():
                walk(c)
        elif children is not None:
            walk(children)

    walk(module)
    return found
