"""bigdl_tpu.nn — module system, layers, criterions.

TPU-native re-design of ``DL/nn/`` (reference: 413 files, 67,616 LoC).
See ``module.py`` for the functional contract that replaces
``AbstractModule``'s mutable forward/backward.
"""

from bigdl_tpu.nn.module import (
    Module, Container, Sequential, Concat, ConcatTable, ParallelTable,
    Identity, Echo, Lambda, Remat,
)
from bigdl_tpu.nn.initialization import (
    InitializationMethod, Zeros, Ones, ConstInitMethod, Xavier, MsraFiller,
    RandomUniform, RandomNormal, BilinearFiller,
)
from bigdl_tpu.nn.layers import (
    Linear, SpatialConvolution, SpatialFullConvolution, SpatialMaxPooling,
    SpatialAveragePooling, SpatialBatchNormalization, BatchNormalization,
    Dropout, LookupTable, SpatialCrossMapLRN, Normalize, NormalizeScale,
    CMul, CAdd,
    TemporalConvolution,
)
from bigdl_tpu.nn.activations import (
    ReLU, ReLU6, Tanh, Sigmoid, SoftMax, LogSoftMax, SoftPlus, SoftSign,
    ELU, LeakyReLU, HardTanh, HardSigmoid, GELU, SiLU, PReLU, RReLU, SReLU,
    Threshold, HardShrink, SoftShrink, LogSigmoid, SoftMin, TanhShrink,
)
from bigdl_tpu.nn.shape_ops import (
    Reshape, View, Flatten, Squeeze, Unsqueeze, Transpose, Contiguous,
    Narrow, Select, Index, Padding, SpatialZeroPadding, JoinTable,
    SplitTable, CAddTable, CMulTable, CSubTable, CDivTable, CMaxTable,
    CMinTable, FlattenTable, SelectTable, MulConstant, AddConstant, Power,
    Sqrt, Square, Abs, Exp, Log, Clamp, Mean, Sum, Max, Min, Replicate,
    Pack, Scale, Masking,
)
from bigdl_tpu.nn.criterion import (
    Criterion, ClassNLLCriterion, CrossEntropyCriterion, MSECriterion,
    AbsCriterion, BCECriterion, BCEWithLogitsCriterion, SmoothL1Criterion,
    DistKLDivCriterion, KLDCriterion, GaussianCriterion, MarginCriterion,
    MarginRankingCriterion, CosineEmbeddingCriterion,
    HingeEmbeddingCriterion, SoftMarginCriterion, L1Cost,
    DiceCoefficientCriterion, MultiLabelSoftMarginCriterion, MultiCriterion,
    ParallelCriterion, TimeDistributedCriterion, PGCriterion,
    MultiLabelMarginCriterion, SoftmaxWithCriterion,
    CosineDistanceCriterion, CosineProximityCriterion, DotProductCriterion,
    KullbackLeiblerDivergenceCriterion, L1HingeEmbeddingCriterion,
    MeanAbsolutePercentageCriterion, MeanSquaredLogarithmicCriterion,
    MultiMarginCriterion, PoissonCriterion, ClassSimplexCriterion,
    SmoothL1CriterionWithWeights, TimeDistributedMaskCriterion,
    TransformerCriterion, CategoricalCrossEntropy,
)
from bigdl_tpu.nn.graph import Graph, DynamicGraph, Input, Node
from bigdl_tpu.nn.control_flow import Cond, Merge, Switch, While
from bigdl_tpu.nn.recurrent import (
    Cell, RnnCell, LSTM, LSTMPeephole, GRU, ConvLSTMPeephole,
    ConvLSTMPeephole3D, MultiRNNCell,
    Recurrent, BiRecurrent, RecurrentDecoder, TimeDistributed,
)
from bigdl_tpu.nn.detection import (
    Anchor, Nms, nms, PriorBox, Proposal, RoiPooling, DetectionOutputSSD,
    DetectionOutputFrcnn,
    bbox_transform_inv, clip_boxes, box_iou,
)
from bigdl_tpu.nn.tree import TreeLSTM, BinaryTreeLSTM
from bigdl_tpu.nn.quantized import (
    quantize, QuantizedLinear, QuantizedSpatialConvolution,
)
from bigdl_tpu.nn.attention import (
    LayerNorm, MultiHeadAttention, dot_product_attention,
)
from bigdl_tpu.nn.regularizers import (
    L1L2Regularizer, L1Regularizer, L2Regularizer, regularization_loss,
)
from bigdl_tpu.nn.sparse import (
    COOBatch, LookupTableSparse, SparseLinear, SparseJoinTable,
    DenseToSparse, coo_row_reduce, coo_spmm, dense_to_bags,
)
from bigdl_tpu.nn.volumetric import (
    VolumetricConvolution, VolumetricMaxPooling, VolumetricAveragePooling,
    VolumetricFullConvolution,
)
from bigdl_tpu.nn.spatial_extras import (
    SpatialDilatedConvolution, SpatialShareConvolution,
    SpatialSeparableConvolution, SpatialConvolutionMap,
    LocallyConnected1D, LocallyConnected2D, SpatialWithinChannelLRN,
    SpatialSubtractiveNormalization, SpatialDivisiveNormalization,
    SpatialContrastiveNormalization, SpatialDropout1D, SpatialDropout2D,
    SpatialDropout3D, UpSampling1D, UpSampling2D, UpSampling3D,
    ResizeBilinear, Cropping2D, Cropping3D, TemporalMaxPooling,
)
from bigdl_tpu.nn.tensor_extras import (
    MM, MV, DotProduct, CrossProduct, PairwiseDistance, CosineDistance,
    Bilinear, Cosine, Euclidean, Add, Mul, Maxout, Highway, MixtureTable,
    MaskedSelect, Reverse, Tile, Negative, InferReshape, NarrowTable,
    CAveTable, BifurcateSplitTable, Bottle, MapTable, GradientReversal,
    GaussianDropout, GaussianNoise, GaussianSampler, L1Penalty,
    NegativeEntropyPenalty, ActivityRegularization, BinaryThreshold,
)
