"""Object-detection heads: Anchor, Nms, PriorBox, Proposal, RoiPooling,
DetectionOutputSSD.

Reference: ``DL/nn/Anchor.scala``, ``Nms.scala``, ``PriorBox.scala``,
``Proposal.scala``, ``RoiPooling.scala``, ``DetectionOutputSSD.scala`` —
the Faster-RCNN / SSD head family.

TPU redesign notes:
- The reference's NMS is a sequential suppressed-flag loop over a sorted
  array (``Nms.scala``) — data-dependent shapes.  XLA needs static shapes,
  so :func:`nms` here is the TPU idiom: ``lax.fori_loop`` over a FIXED
  number of output slots, each iteration argmax-ing the best remaining box
  and masking its overlaps.  Output is ``(indices, valid_mask)`` of static
  length — consumers mask rather than slice.
- RoiPooling avoids per-RoI ragged dynamic slices (recompilation storms)
  by computing each pooled bin as a masked max over the full feature map —
  dense, vectorized over RoIs via broadcasting, MXU/VPU friendly.
- Proposal keeps top-k/bbox decode inside one jit region; "filter boxes
  smaller than min_size" becomes score-masking instead of compaction.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.module import Module


# --------------------------------------------------------------- bbox utils
def bbox_transform_inv(boxes: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """Decode (dx, dy, dw, dh) deltas against anchor boxes (x1, y1, x2, y2)
    (reference ``BboxUtil.bboxTransformInv``)."""
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * w
    cy = boxes[:, 1] + 0.5 * h
    dx, dy, dw, dh = (deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3])
    pcx = dx * w + cx
    pcy = dy * h + cy
    pw = jnp.exp(dw) * w
    ph = jnp.exp(dh) * h
    return jnp.stack([pcx - 0.5 * pw, pcy - 0.5 * ph,
                      pcx + 0.5 * pw, pcy + 0.5 * ph], axis=1)


def clip_boxes(boxes: jnp.ndarray, im_h: float, im_w: float) -> jnp.ndarray:
    """Clip boxes to image bounds (reference ``BboxUtil.clipBoxes``)."""
    x1 = jnp.clip(boxes[:, 0], 0.0, im_w - 1.0)
    y1 = jnp.clip(boxes[:, 1], 0.0, im_h - 1.0)
    x2 = jnp.clip(boxes[:, 2], 0.0, im_w - 1.0)
    y2 = jnp.clip(boxes[:, 3], 0.0, im_h - 1.0)
    return jnp.stack([x1, y1, x2, y2], axis=1)


def box_iou(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU between (N,4) and (M,4) corner boxes, +1 pixel
    convention matching the reference's area computation."""
    area_a = ((a[:, 2] - a[:, 0] + 1.0) * (a[:, 3] - a[:, 1] + 1.0))[:, None]
    area_b = ((b[:, 2] - b[:, 0] + 1.0) * (b[:, 3] - b[:, 1] + 1.0))[None, :]
    ix = (jnp.minimum(a[:, None, 2], b[None, :, 2])
          - jnp.maximum(a[:, None, 0], b[None, :, 0]) + 1.0)
    iy = (jnp.minimum(a[:, None, 3], b[None, :, 3])
          - jnp.maximum(a[:, None, 1], b[None, :, 1]) + 1.0)
    inter = jnp.maximum(ix, 0.0) * jnp.maximum(iy, 0.0)
    return inter / (area_a + area_b - inter)


# ---------------------------------------------------------------------- NMS
def nms(boxes: jnp.ndarray, scores: jnp.ndarray, iou_threshold: float,
        max_output: int, iou: Optional[jnp.ndarray] = None):
    """Static-shape NMS (TPU redesign of ``Nms.scala``'s suppressed-flag
    loop).  Returns ``(indices, valid)``: ``indices`` has length
    ``max_output``; ``valid[i]`` is False for unused slots.  Pass a
    precomputed pairwise ``iou`` when suppressing the same boxes under
    several score sets (per-class SSD) to avoid recomputing the N×N
    matrix."""
    n = boxes.shape[0]
    if iou is None:
        iou = box_iou(boxes, boxes)
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)

    def body(i, carry):
        live_scores, out_idx, out_valid = carry
        best = jnp.argmax(live_scores)
        ok = live_scores[best] > neg_inf
        out_idx = out_idx.at[i].set(jnp.where(ok, best, -1))
        out_valid = out_valid.at[i].set(ok)
        # suppress the chosen box and everything overlapping it
        suppress = (iou[best] > iou_threshold) | \
            (jnp.arange(n) == best)
        live_scores = jnp.where(ok & suppress, neg_inf, live_scores)
        return live_scores, out_idx, out_valid

    _, idx, valid = lax.fori_loop(
        0, max_output, body,
        (scores.astype(jnp.float32),
         jnp.full((max_output,), -1, jnp.int32),
         jnp.zeros((max_output,), bool)))
    return idx, valid


class Nms:
    """Object-style wrapper (reference ``Nms.scala`` API)."""

    def __call__(self, scores, boxes, thresh: float, max_output: int):
        return nms(boxes, scores, thresh, max_output)


# ------------------------------------------------------------------- Anchor
class Anchor:
    """Faster-RCNN anchor generator (reference ``Anchor.scala:25``):
    enumerate ratios x scales around a ``base_size`` box, then shift over
    the feature-map grid."""

    def __init__(self, ratios: Sequence[float], scales: Sequence[float],
                 base_size: int = 16):
        self.ratios = list(ratios)
        self.scales = list(scales)
        self.base_size = base_size
        self.anchor_num = len(ratios) * len(scales)
        self.basic_anchors = self._generate_basic()  # (A, 4) np

    def _generate_basic(self) -> np.ndarray:
        """ratio enumeration then scale enumeration, rounding like the
        reference (``generateBasicAnchors``/``ratioEnum``/``scaleEnum``)."""
        base = np.array([0.0, 0.0, self.base_size - 1.0,
                         self.base_size - 1.0])
        w = base[2] - base[0] + 1
        h = base[3] - base[1] + 1
        cx = base[0] + 0.5 * (w - 1)
        cy = base[1] + 0.5 * (h - 1)
        area = w * h
        out = []
        for r in self.ratios:
            ws = round(math.sqrt(area / r))
            hs = round(ws * r)
            for s in self.scales:
                wss, hss = ws * s, hs * s
                out.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
        return np.asarray(out, np.float32)

    def generate_anchors(self, width: int, height: int,
                         feat_stride: float = 16.0) -> jnp.ndarray:
        """All anchors for a (height, width) feature map: (W*H*A, 4),
        shifts enumerated x-fastest then y (reference
        ``Anchor.generateAnchors:38``)."""
        sx = jnp.arange(width, dtype=jnp.float32) * feat_stride
        sy = jnp.arange(height, dtype=jnp.float32) * feat_stride
        shift_x, shift_y = jnp.meshgrid(sx, sy)  # (H, W)
        shifts = jnp.stack([shift_x, shift_y, shift_x, shift_y],
                           axis=-1).reshape(-1, 4)  # (H*W, 4)
        a = jnp.asarray(self.basic_anchors)  # (A, 4)
        return (shifts[:, None, :] + a[None, :, :]).reshape(-1, 4)


# ----------------------------------------------------------------- PriorBox
class PriorBox(Module):
    """SSD prior boxes for one feature map (reference ``PriorBox.scala:41``).
    Output matches Caffe/reference layout: ``(1, 2, H*W*P*4)`` — row 0 the
    normalized priors, row 1 the per-coordinate variances."""

    def __init__(self, min_sizes: Sequence[float],
                 max_sizes: Optional[Sequence[float]] = None,
                 aspect_ratios: Optional[Sequence[float]] = None,
                 is_flip: bool = True, is_clip: bool = False,
                 variances: Optional[Sequence[float]] = None,
                 offset: float = 0.5,
                 img_h: int = 0, img_w: int = 0, img_size: int = 0,
                 step_h: float = 0.0, step_w: float = 0.0, step: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.min_sizes = list(min_sizes)
        self.max_sizes = list(max_sizes or [])
        ars = [1.0]
        for ar in (aspect_ratios or []):
            if any(abs(ar - e) < 1e-6 for e in ars):
                continue
            ars.append(ar)
            if is_flip:
                ars.append(1.0 / ar)
        self.aspect_ratios = ars
        self.is_clip = is_clip
        self.variances = list(variances or [0.1])
        self.offset = offset
        self.img_h, self.img_w = (img_h or img_size), (img_w or img_size)
        self.step_h, self.step_w = (step_h or step), (step_w or step)
        # priors per cell: one per min_size per aspect ratio + one per max_size
        self.n_priors = (len(self.min_sizes) * len(self.aspect_ratios)
                         + len(self.max_sizes))

    def apply(self, params, state, input, *, training=False, rng=None):
        # input: the feature map (N, C, H, W) — only its H/W are used
        fh, fw = input.shape[2], input.shape[3]
        img_h, img_w = self.img_h, self.img_w
        step_h = self.step_h or img_h / fh
        step_w = self.step_w or img_w / fw

        widths, heights = [], []
        for ms in self.min_sizes:
            for ar in self.aspect_ratios:
                if abs(ar - 1.0) < 1e-6:
                    widths.append(ms)
                    heights.append(ms)
                else:
                    widths.append(ms * math.sqrt(ar))
                    heights.append(ms / math.sqrt(ar))
            # between min and max (the sqrt prior), once per min_size
            if self.max_sizes:
                mx = self.max_sizes[self.min_sizes.index(ms)]
                widths.append(math.sqrt(ms * mx))
                heights.append(math.sqrt(ms * mx))
        w = jnp.asarray(widths, jnp.float32) * 0.5
        h = jnp.asarray(heights, jnp.float32) * 0.5

        cx = (jnp.arange(fw, dtype=jnp.float32) + self.offset) * step_w
        cy = (jnp.arange(fh, dtype=jnp.float32) + self.offset) * step_h
        gx, gy = jnp.meshgrid(cx, cy)  # (fh, fw)
        centers = jnp.stack([gx, gy], -1).reshape(-1, 2)  # (fh*fw, 2)

        x1 = (centers[:, None, 0] - w[None, :]) / img_w
        y1 = (centers[:, None, 1] - h[None, :]) / img_h
        x2 = (centers[:, None, 0] + w[None, :]) / img_w
        y2 = (centers[:, None, 1] + h[None, :]) / img_h
        priors = jnp.stack([x1, y1, x2, y2], -1)  # (cells, P, 4)
        if self.is_clip:
            priors = jnp.clip(priors, 0.0, 1.0)
        flat = priors.reshape(-1)

        if len(self.variances) == 1:
            var = jnp.full_like(flat, self.variances[0])
        else:
            var = jnp.tile(jnp.asarray(self.variances, jnp.float32),
                           flat.shape[0] // 4)
        return jnp.stack([flat, var])[None], state


# ----------------------------------------------------------------- Proposal
class Proposal(Module):
    """RPN proposal layer (reference ``Proposal.scala:34``).  Input:
    ``(scores (1, 2A, H, W), bbox_deltas (1, 4A, H, W),
    im_info (1, >=4) = [im_h, im_w, scale_h, scale_w])``.
    Output: ``(boxes (post_nms_topn, 5), valid (post_nms_topn,))`` where
    column 0 is the batch index (always 0 — single image, like the
    reference) — static shape, masked instead of truncated."""

    def __init__(self, pre_nms_topn: int, post_nms_topn: int,
                 ratios: Sequence[float], scales: Sequence[float],
                 min_size: int = 16, nms_thresh: float = 0.7,
                 feat_stride: float = 16.0, name: Optional[str] = None):
        super().__init__(name)
        self.pre_nms_topn = pre_nms_topn
        self.post_nms_topn = post_nms_topn
        self.anchor = Anchor(ratios, scales)
        self.min_size = min_size
        self.nms_thresh = nms_thresh
        self.feat_stride = feat_stride

    def apply(self, params, state, input, *, training=False, rng=None):
        scores, deltas, im_info = input
        A = self.anchor.anchor_num
        H, W = scores.shape[2], scores.shape[3]
        # fg scores are the second half of the 2A channel block
        fg = scores[0, A:]                         # (A, H, W)
        fg = jnp.transpose(fg, (1, 2, 0)).reshape(-1)  # match anchor order
        d = deltas[0].reshape(A, 4, H, W)
        d = jnp.transpose(d, (2, 3, 0, 1)).reshape(-1, 4)

        anchors = self.anchor.generate_anchors(W, H, self.feat_stride)
        proposals = bbox_transform_inv(anchors, d)
        im_h, im_w = im_info[0, 0], im_info[0, 1]
        proposals = clip_boxes(proposals, im_h, im_w)

        # reference filters boxes < min_size * im_scale; here: mask scores
        ws = proposals[:, 2] - proposals[:, 0] + 1.0
        hs = proposals[:, 3] - proposals[:, 1] + 1.0
        min_h = self.min_size * im_info[0, 2]
        min_w = self.min_size * im_info[0, 3]
        keep = (ws >= min_w) & (hs >= min_h)
        fg = jnp.where(keep, fg, -jnp.inf)

        k = min(self.pre_nms_topn, fg.shape[0])
        top_scores, top_idx = lax.top_k(fg, k)
        top_boxes = proposals[top_idx]

        idx, valid = nms(top_boxes, top_scores, self.nms_thresh,
                         self.post_nms_topn)
        out_boxes = top_boxes[jnp.maximum(idx, 0)]
        out = jnp.concatenate(
            [jnp.zeros((self.post_nms_topn, 1), out_boxes.dtype), out_boxes],
            axis=1)
        out = out * valid[:, None].astype(out.dtype)
        return (out, valid), state


# --------------------------------------------------------------- RoiPooling
class RoiPooling(Module):
    """RoI max pooling (reference ``RoiPooling.scala:42``).  Input:
    ``(data (N, C, H, W), rois (R, 5) = [batch_idx, x1, y1, x2, y2])``;
    output ``(R, C, pooled_h, pooled_w)``.

    TPU design: each pooled bin = masked max over the full (H, W) map —
    no ragged dynamic slices, fully vectorized over RoIs."""

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float,
                 name: Optional[str] = None):
        super().__init__(name)
        self.pooled_w = pooled_w
        self.pooled_h = pooled_h
        self.spatial_scale = spatial_scale

    def apply(self, params, state, input, *, training=False, rng=None):
        data, rois = input
        H, W = data.shape[2], data.shape[3]
        batch_idx = rois[:, 0].astype(jnp.int32)
        feats = jnp.take(data, batch_idx, axis=0)      # (R, C, H, W)

        # RoI bounds on the feature map (reference rounds them)
        x1 = jnp.round(rois[:, 1] * self.spatial_scale)
        y1 = jnp.round(rois[:, 2] * self.spatial_scale)
        x2 = jnp.round(rois[:, 3] * self.spatial_scale)
        y2 = jnp.round(rois[:, 4] * self.spatial_scale)
        roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
        roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_w = roi_w / self.pooled_w
        bin_h = roi_h / self.pooled_h

        ph = jnp.arange(self.pooled_h, dtype=jnp.float32)
        pw = jnp.arange(self.pooled_w, dtype=jnp.float32)
        # bin boundaries, clipped to the map (reference floor/ceil + clamp)
        hstart = jnp.clip(jnp.floor(ph[None] * bin_h[:, None])
                          + y1[:, None], 0, H)          # (R, ph)
        hend = jnp.clip(jnp.ceil((ph[None] + 1) * bin_h[:, None])
                        + y1[:, None], 0, H)
        wstart = jnp.clip(jnp.floor(pw[None] * bin_w[:, None])
                          + x1[:, None], 0, W)          # (R, pw)
        wend = jnp.clip(jnp.ceil((pw[None] + 1) * bin_w[:, None])
                        + x1[:, None], 0, W)

        gy = jnp.arange(H, dtype=jnp.float32)
        gx = jnp.arange(W, dtype=jnp.float32)
        mask_h = ((gy[None, None, :] >= hstart[:, :, None])
                  & (gy[None, None, :] < hend[:, :, None]))  # (R, ph, H)
        mask_w = ((gx[None, None, :] >= wstart[:, :, None])
                  & (gx[None, None, :] < wend[:, :, None]))  # (R, pw, W)

        # the bin mask is separable in H and W, so chain two masked maxes
        # instead of materializing the (R, C, ph, pw, H, W) product — peak
        # memory O(R*C*ph*H*W), which real Faster-RCNN shapes need
        neg = jnp.asarray(-jnp.inf, data.dtype)
        # reduce H: (R, C, H, W) with (R, ph, H) -> (R, C, ph, W)
        rows = jnp.where(mask_h[:, None, :, :, None],
                         feats[:, :, None], neg).max(axis=3)
        # reduce W: (R, C, ph, W) with (R, pw, W) -> (R, C, ph, pw)
        out = jnp.where(mask_w[:, None, None, :, :],
                        rows[:, :, :, None], neg).max(axis=-1)
        # empty bins (hstart>=hend) pool to 0 like the reference
        return jnp.where(jnp.isfinite(out), out, 0.0), state


def _global_topk(dets: jnp.ndarray, valid: jnp.ndarray, k: int):
    """Keep the k highest-scoring rows of (dets (M, 6), valid (M,)),
    zero-padding to static k (shared by the SSD/FRCNN output heads;
    column 1 is the score)."""
    masked = jnp.where(valid, dets[:, 1], -jnp.inf)
    kk = min(k, masked.shape[0])
    top_s, top_i = lax.top_k(masked, kk)
    out = dets[top_i] * jnp.isfinite(top_s)[:, None]
    out_valid = jnp.isfinite(top_s)
    if kk < k:
        pad = k - kk
        out = jnp.concatenate([out, jnp.zeros((pad, 6))])
        out_valid = jnp.concatenate([out_valid, jnp.zeros((pad,), bool)])
    return out, out_valid


# ------------------------------------------------------- DetectionOutputSSD
class DetectionOutputSSD(Module):
    """SSD post-processing (reference ``DetectionOutputSSD.scala:49``).
    Input: ``(loc (N, P*4), conf (N, P*n_classes), priors (1, 2, P*4))``.
    Output: ``(dets (N, keep_topk, 6) = [label, score, x1, y1, x2, y2],
    valid (N, keep_topk))`` — static shape, masked."""

    def __init__(self, n_classes: int = 21, share_location: bool = True,
                 bg_label: int = 0, nms_thresh: float = 0.45,
                 nms_topk: int = 400, keep_topk: int = 200,
                 conf_thresh: float = 0.01,
                 variance_encoded_in_target: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        if not share_location:
            raise NotImplementedError("share_location=False not supported")
        self.n_classes = n_classes
        self.bg_label = bg_label
        self.nms_thresh = nms_thresh
        self.nms_topk = nms_topk
        self.keep_topk = keep_topk
        self.conf_thresh = conf_thresh
        self.variance_encoded = variance_encoded_in_target

    def _decode(self, loc, priors, variances):
        """Caffe-style center-size decode (reference ``BboxUtil.decodeBoxes``)."""
        pw = priors[:, 2] - priors[:, 0]
        ph = priors[:, 3] - priors[:, 1]
        pcx = (priors[:, 0] + priors[:, 2]) * 0.5
        pcy = (priors[:, 1] + priors[:, 3]) * 0.5
        v = jnp.ones_like(loc) if self.variance_encoded else variances
        cx = v[:, 0] * loc[:, 0] * pw + pcx
        cy = v[:, 1] * loc[:, 1] * ph + pcy
        w = jnp.exp(v[:, 2] * loc[:, 2]) * pw
        h = jnp.exp(v[:, 3] * loc[:, 3]) * ph
        return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], 1)

    def apply(self, params, state, input, *, training=False, rng=None):
        loc, conf, priors = input
        N = loc.shape[0]
        P = priors.shape[2] // 4
        prior_boxes = priors[0, 0].reshape(P, 4)
        prior_vars = priors[0, 1].reshape(P, 4)

        def one_image(loc_i, conf_i):
            boxes = self._decode(loc_i.reshape(P, 4), prior_boxes,
                                 prior_vars)
            scores = conf_i.reshape(P, self.n_classes)
            # share_location: every class suppresses the SAME boxes, so
            # the P×P IoU matrix is computed once, not per class
            iou = box_iou(boxes, boxes)
            all_dets, all_valid = [], []
            per_class = max(1, self.nms_topk // max(1, self.n_classes - 1))
            for c in range(self.n_classes):
                if c == self.bg_label:
                    continue
                s = jnp.where(scores[:, c] >= self.conf_thresh,
                              scores[:, c], -jnp.inf)
                idx, valid = nms(boxes, s, self.nms_thresh, per_class,
                                 iou=iou)
                b = boxes[jnp.maximum(idx, 0)]
                sc = scores[jnp.maximum(idx, 0), c]
                det = jnp.concatenate(
                    [jnp.full((per_class, 1), float(c)), sc[:, None], b], 1)
                all_dets.append(det)
                all_valid.append(valid)
            dets = jnp.concatenate(all_dets)          # (C*per_class, 6)
            valid = jnp.concatenate(all_valid)
            return _global_topk(dets, valid, self.keep_topk)

        dets, valid = jax.vmap(one_image)(loc, conf)
        return (dets, valid), state


# --------------------------------------------------- DetectionOutputFrcnn
class DetectionOutputFrcnn(Module):
    """Faster-RCNN post-processing (reference
    ``DetectionOutputFrcnn.scala:48``).  Input:
    ``(im_info (1, >=4), rois (R, 5) [batch, x1, y1, x2, y2],
    bbox_deltas (R, 4*n_classes), scores (R, n_classes))``.
    Output: ``(dets (max_per_image, 6) = [label, score, x1, y1, x2, y2],
    valid (max_per_image,))`` — static shapes, masked.

    Unlike SSD's share_location head, every class has its OWN box
    regression (per-class 4-delta slice), per-class NMS at ``nms_thresh``,
    a score floor ``thresh``, and a global top-``max_per_image`` cut.
    """

    def __init__(self, nms_thresh: float = 0.3, n_classes: int = 21,
                 max_per_image: int = 100, thresh: float = 0.05,
                 name: Optional[str] = None):
        super().__init__(name)
        self.nms_thresh = nms_thresh
        self.n_classes = n_classes
        self.max_per_image = max_per_image
        self.thresh = thresh

    def apply(self, params, state, input, *, training=False, rng=None):
        im_info, rois, deltas, scores = input
        R = rois.shape[0]
        im_h, im_w = im_info[0, 0], im_info[0, 1]
        boxes = rois[:, 1:5]
        # faithful to the reference: per-class NMS is UNBOUNDED (every roi
        # may survive); only the global max_per_image cut limits output
        per_class = min(R, self.max_per_image)
        all_dets, all_valid = [], []
        for c in range(1, self.n_classes):  # 0 = background
            d = deltas[:, 4 * c:4 * (c + 1)]
            decoded = clip_boxes(bbox_transform_inv(boxes, d), im_h, im_w)
            s = jnp.where(scores[:, c] > self.thresh, scores[:, c],
                          -jnp.inf)
            idx, valid = nms(decoded, s, self.nms_thresh, per_class)
            b = decoded[jnp.maximum(idx, 0)]
            sc = scores[jnp.maximum(idx, 0), c]
            det = jnp.concatenate(
                [jnp.full((per_class, 1), float(c)), sc[:, None], b], 1)
            all_dets.append(det)
            all_valid.append(valid)
        dets = jnp.concatenate(all_dets)
        valid = jnp.concatenate(all_valid)
        return _global_topk(dets, valid, self.max_per_image), state
