"""User-facing control-flow modules for hand-built graphs.

Reference: ``DynamicGraph`` + ``Scheduler.scala:104-145`` — the
reference executes Enter/Exit/Switch/Merge control-flow NODES with a
scheduler that propagates "dead" tokens through untaken branches.

TPU redesign: under XLA, control flow must be part of the compiled
program, so the scheduler's roles map onto three constructs:

- :class:`While` — a loop frame (Enter/Merge/LoopCond/NextIteration/
  Exit collapses into one module).  With ``max_trip_count`` it compiles
  to a bounded ``lax.scan`` whose post-exit iterations are skipped via
  ``lax.cond`` — data-dependent exit AND reverse-mode differentiable,
  so loop graphs TRAIN (the reference's dynamic graphs cannot generate
  a backward graph through control flow at all,
  ``DynamicGraph.scala backwardExecution``); without it, a
  ``lax.while_loop`` (forward-only, a JAX fundamental).
- :class:`Cond` — branching via ``lax.cond`` (one branch executes;
  differentiable).
- :class:`Switch` / :class:`Merge` — the reference's port semantics as
  dataflow: both branch subgraphs compute and Merge SELECTS (dead-token
  propagation becomes ``jnp.where``, which is how the TF importer
  compiles the same ops, ``interop/tf_format.py`` _exec_switch/_merge).

All three are ordinary :class:`Module`s: use them as ``Graph`` nodes or
inside ``Sequential``.  ``rng`` is forwarded to children (per-iteration
``fold_in`` inside loops), and Module predicates/conditions run with
the caller's ``training`` flag, their state threaded like any child's.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module


def _as_pred(v):
    return jnp.reshape(jnp.asarray(v, bool), ())


class While(Module):
    """``while cond(carry): carry = body(carry)`` as a module.

    - ``cond``: callable ``carry -> bool scalar`` or a Module (applied
      with the caller's ``training`` flag; its state is threaded
      through the loop like the body's);
    - ``body``: Module mapping carry -> carry (same pytree structure
      and shapes — XLA loops are shape-invariant);
    - ``max_trip_count``: when given, the loop runs as a bounded
      ``lax.scan`` where iterations past the exit condition SKIP the
      body via ``lax.cond`` (not just mask its output — a diverging
      body after exit would otherwise poison gradients with inf/NaN
      through the select).  This is the differentiable form — use it
      for training.  When None, a ``lax.while_loop`` executes exactly
      like the reference's frame scheduler (forward-only).
    """

    def __init__(self, cond: Union[Callable, Module], body: Module,
                 max_trip_count: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name or "While")
        self.cond = cond
        self.body = body
        self.max_trip_count = max_trip_count

    def spec_children(self):
        out = {"body": self.body}
        if isinstance(self.cond, Module):
            out["cond"] = self.cond
        return out

    def init(self, rng):
        params, state = {}, {}
        k1, k2 = jax.random.split(rng)
        params["body"], state["body"] = self.body.init(k1)
        if isinstance(self.cond, Module):
            params["cond"], state["cond"] = self.cond.init(k2)
        return params, state

    def _cond_value(self, params, cstate, carry, training):
        if isinstance(self.cond, Module):
            out, cstate = self.cond.apply(params.get("cond", {}), cstate,
                                          carry, training=training)
            return _as_pred(out), cstate
        return _as_pred(self.cond(carry)), cstate

    def apply(self, params, state, input, *, training=False, rng=None):
        body_state = state.get("body", {})
        cond_state = state.get("cond", {})
        it0 = jnp.zeros((), jnp.int32)

        def run_body(carry, bst, it):
            r = None if rng is None else jax.random.fold_in(rng, it)
            return self.body.apply(params["body"], bst, carry,
                                   training=training, rng=r)

        if self.max_trip_count is None:
            # liveness rides the carry so the predicate runs exactly
            # once per trip (a cond_fn predicate would be re-evaluated
            # on top of the state-threading evaluation in the body)
            live0, cond_state = self._cond_value(params, cond_state,
                                                 input, training)

            def cond_fn(c):
                return c[4]

            def body_fn(c):
                carry, bst, cst, it, _ = c
                out, bst = run_body(carry, bst, it)
                live, cst = self._cond_value(params, cst, out, training)
                return (out, bst, cst, it + 1, live)

            carry, body_state, cond_state, _, _ = lax.while_loop(
                cond_fn, body_fn,
                (input, body_state, cond_state, it0, live0))
        else:
            # bounded loop: live iterations run the body, dead ones are
            # skipped entirely (lax.cond) — differentiable end to end.
            # The predicate's state also freezes once the loop is dead,
            # matching the unbounded path's per-trip semantics.
            def scan_body(c, _):
                carry, bst, cst, it = c
                live, cst_new = self._cond_value(params, cst, carry,
                                                 training)
                cst = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(live, new, old),
                    cst_new, cst)

                def taken(operand):
                    carry, bst, it = operand
                    out, bst = run_body(carry, bst, it)
                    return out, bst

                def skipped(operand):
                    carry, bst, it = operand
                    return carry, bst

                out, bst = lax.cond(live, taken, skipped,
                                    (carry, bst, it))
                return (out, bst, cst, it + 1), None

            (carry, body_state, cond_state, _), _ = lax.scan(
                scan_body, (input, body_state, cond_state, it0), None,
                length=self.max_trip_count)

        new_state = dict(state)
        new_state["body"] = body_state
        if isinstance(self.cond, Module):
            new_state["cond"] = cond_state
        return carry, new_state


class Cond(Module):
    """``true_branch(input) if pred(input) else false_branch(input)``
    via ``lax.cond`` — only the taken branch executes; both branches
    must produce the same output structure/shapes."""

    def __init__(self, pred: Union[Callable, Module], true_branch: Module,
                 false_branch: Module, name: Optional[str] = None):
        super().__init__(name or "Cond")
        self.pred = pred
        self.true_branch = true_branch
        self.false_branch = false_branch

    def spec_children(self):
        out = {"true": self.true_branch, "false": self.false_branch}
        if isinstance(self.pred, Module):
            out["pred"] = self.pred
        return out

    def init(self, rng):
        params, state = {}, {}
        k1, k2, k3 = jax.random.split(rng, 3)
        params["true"], state["true"] = self.true_branch.init(k1)
        params["false"], state["false"] = self.false_branch.init(k2)
        if isinstance(self.pred, Module):
            params["pred"], state["pred"] = self.pred.init(k3)
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        new_state = dict(state)
        if isinstance(self.pred, Module):
            pv, pstate = self.pred.apply(params["pred"],
                                         state.get("pred", {}), input,
                                         training=training)
            new_state["pred"] = pstate
        else:
            pv = self.pred(input)
        pv = _as_pred(pv)
        kt, kf = (None, None) if rng is None else jax.random.split(rng)

        def true_fn(x):
            out, st = self.true_branch.apply(
                params["true"], state["true"], x, training=training,
                rng=kt)
            return out, st, state["false"]

        def false_fn(x):
            out, st = self.false_branch.apply(
                params["false"], state["false"], x, training=training,
                rng=kf)
            return out, state["true"], st

        out, t_state, f_state = lax.cond(pv, true_fn, false_fn, input)
        new_state["true"], new_state["false"] = t_state, f_state
        return out, new_state


class Switch(Module):
    """Reference ``Switch`` port semantics as dataflow: input
    ``(data, pred)`` → output ``(data_port0, data_port1)`` feeding the
    false/true subgraphs.  Under XLA both branch subgraphs compute (no
    dead tokens); pair with :class:`Merge` which performs the select —
    the same compilation the TF importer applies to imported
    Switch/Merge nodes."""

    def apply(self, params, state, input, *, training=False, rng=None):
        data, pred = input
        return (data, data), state


class Merge(Module):
    """Reference ``Merge``: pick the live branch.  Input
    ``(false_val, true_val, pred)`` → ``where(pred, true_val,
    false_val)`` (elementwise select replaces dead-token scheduling)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        false_val, true_val, pred = input
        pred = _as_pred(pred)
        return jax.tree_util.tree_map(
            lambda t, f: jnp.where(pred, t, f), true_val, false_val), state
