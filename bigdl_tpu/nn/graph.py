"""Graph — DAG models.

Reference: ``DL/nn/Graph.scala:72`` (742 LoC) + ``StaticGraph.scala`` —
models built from ``Node[AbstractModule]`` with a precomputed
``topologySort`` and a *generated backward graph* (``Graph.scala:196``).

TPU redesign: the backward graph dies (jax.grad differentiates the forward
trace); what remains is a declarative DAG executed in topological order
inside ``apply``.  The reference's ``DynamicGraph``+``Scheduler`` execute
TF-style control-flow frames (Enter/Exit/Switch/Merge,
``nn/Scheduler.scala:104-145``); under XLA data-dependent control flow maps
to ``lax.cond``/``lax.while_loop`` inside a module's ``apply`` instead of
graph-level scheduling, so only the static DAG is needed here.

Usage (mirrors the reference's functional graph API)::

    inp = Input()
    h = Linear(4, 8)(inp)          # Module.__call__ on Node -> Node
    a = ReLU()(h)
    b = Tanh()(h)
    out = CAddTable()([a, b])      # multi-input: list of Nodes
    model = Graph([inp], [out])
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax

from bigdl_tpu.nn.module import Module


class Node:
    """A module instance + its input edges."""

    __slots__ = ("module", "inputs")

    def __init__(self, module: Optional[Module], inputs: Sequence["Node"]):
        self.module = module
        self.inputs = list(inputs)

    def __repr__(self):
        name = self.module.name if self.module else "Input"
        return f"Node({name})"


class Input(Node):
    """Graph input placeholder (reference ``nn/Input.scala``)."""

    def __init__(self):
        super().__init__(None, [])


class Graph(Module):
    """Static DAG container (reference ``StaticGraph.scala:35``).

    The ``module(node)`` call syntax that builds :class:`Node` edges is
    implemented in ``Module.__call__`` (module.py) via a Node isinstance
    check.

    **Weight sharing:** using the SAME module instance at several graph
    positions ties the weights (reference semantics — a module owns its
    weights), implemented by keying params by the module's first
    occurrence."""

    def __init__(self, inputs: Sequence[Node], outputs: Sequence[Node],
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_nodes = list(inputs)
        self.output_nodes = list(outputs)
        self._order = self._topo_sort()
        # modules in execution order (Input nodes excluded)
        self.modules = [n.module for n in self._order]
        # param key per node: nodes sharing a module instance share params
        self._param_keys: list[str] = []
        first_seen: dict[int, str] = {}
        for i, n in enumerate(self._order):
            key = first_seen.setdefault(id(n.module), str(i))
            self._param_keys.append(key)

    def _topo_sort(self) -> list[Node]:
        """Reverse-DFS from outputs (reference ``forwardGraph.topologySort``,
        ``StaticGraph.scala:41``)."""
        visited: dict[int, int] = {}  # id -> 0 visiting, 1 done
        order: list[Node] = []

        def visit(n: Node):
            key = id(n)
            st = visited.get(key)
            if st == 1:
                return
            if st == 0:
                raise ValueError("graph contains a cycle")
            visited[key] = 0
            for p in n.inputs:
                visit(p)
            visited[key] = 1
            if n.module is not None:
                order.append(n)
            elif n not in self.input_nodes:
                raise ValueError("dangling Input node not listed in inputs")

        for out in self.output_nodes:
            visit(out)
        return order

    def spec_children(self):
        out = {}
        for i, node in enumerate(self._order):
            out.setdefault(self._param_keys[i], node.module)
        return out

    def init(self, rng):
        params, state = {}, {}
        for i, node in enumerate(self._order):
            key = self._param_keys[i]
            if key in params:  # shared module: weights tied
                continue
            rng, sub = jax.random.split(rng)
            p, s = node.module.init(sub)
            params[key] = p
            state[key] = s
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        # bind graph inputs
        values: dict[int, object] = {}
        if len(self.input_nodes) == 1:
            values[id(self.input_nodes[0])] = input
        else:
            if len(input) != len(self.input_nodes):
                raise ValueError(
                    f"graph expects {len(self.input_nodes)} inputs, "
                    f"got {len(input)}")
            for node, x in zip(self.input_nodes, input):
                values[id(node)] = x

        rngs = ([None] * len(self._order) if rng is None
                else list(jax.random.split(rng, len(self._order))))
        new_state = {}
        for i, node in enumerate(self._order):
            key = self._param_keys[i]
            args = [values[id(p)] for p in node.inputs]
            x = args[0] if len(args) == 1 else tuple(args)
            # shared module instances (weight tying) share a state key: a
            # later occurrence must see the earlier occurrence's update
            # (running BN stats apply sequentially), not the stale input
            # state — reference shared-instance semantics
            cur_state = new_state.get(key, state[key])
            out, s = node.module.apply(params[key], cur_state, x,
                                       training=training, rng=rngs[i])
            values[id(node)] = out
            new_state[key] = s

        outs = [values[id(n)] for n in self.output_nodes]
        return (outs[0] if len(outs) == 1 else tuple(outs)), new_state


class DynamicGraph(Graph):
    """Graph whose nodes may be control-flow modules (reference
    ``DynamicGraph.scala`` + ``Scheduler.scala:104-145``).

    The reference needs a separate dynamic graph executor because its
    static graph precomputes a topological order that cannot express
    data-dependent control flow; the ``Scheduler`` then interprets
    Enter/Exit/Switch/Merge frames node-by-node with dead-token
    propagation.  Under XLA that split disappears: data-dependent
    control flow lives INSIDE compiled nodes —
    :class:`~bigdl_tpu.nn.control_flow.While` (a whole loop frame, as a
    bounded masked scan it even TRAINS, which the reference's dynamic
    graphs cannot), :class:`~bigdl_tpu.nn.control_flow.Cond`, and the
    port-semantic :class:`~bigdl_tpu.nn.control_flow.Switch` /
    :class:`~bigdl_tpu.nn.control_flow.Merge` pair — so the scheduler's
    graph-level role reduces to the same topological execution
    :class:`Graph` already performs.  Imported TF control flow compiles
    identically (``interop.tf_format``: Switch/Merge → select, loop
    frames → ``lax.while_loop``/``lax.scan``).
    """
