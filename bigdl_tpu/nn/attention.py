"""Attention layers.

No reference analog — BigDL v0.x predates transformers (SURVEY §5:
"no attention, no ring/Ulysses/blockwise anything") — but long-context and
distributed are first-class in the TPU build, so attention is core nn
surface.  Sequence-parallel execution lives in
``bigdl_tpu.parallel.ring_attention``; this module is the single-device
math it distributes.

Layout: (N, T, D) batch-major, heads split internally to (N, H, T, Dh).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.initialization import Xavier


class LayerNorm(Module):
    """Layer normalization over the last dim (standard transformer norm;
    the reference's closest is ``Normalize``)."""

    def __init__(self, normalized_size: int, eps: float = 1e-5,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = normalized_size
        self.eps = eps

    def init(self, rng):
        return {"weight": jnp.ones((self.size,), jnp.float32),
                "bias": jnp.zeros((self.size,), jnp.float32)}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        # normalize in f32 for bf16 stability, cast back
        x = input.astype(jnp.float32)
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["weight"] + params["bias"]
        return y.astype(input.dtype), state


def dot_product_attention(q, k, v, *, causal: bool = False,
                          mask: Optional[jnp.ndarray] = None,
                          scale: Optional[float] = None):
    """Softmax attention. q,k,v: (N, H, Tq, Dh)/(N, H, Tk, Dh).
    Softmax statistics in f32 (bf16-safe)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("nhqd,nhkd->nhqk", q, k).astype(jnp.float32) * scale
    if causal:
        Tq, Tk = scores.shape[-2], scores.shape[-1]
        # offset supports Tq != Tk (decode: query tail of the sequence)
        qi = jnp.arange(Tq)[:, None] + (Tk - Tq)
        ki = jnp.arange(Tk)[None, :]
        scores = jnp.where(ki <= qi, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("nhqk,nhkd->nhqd", w, v)


class MultiHeadAttention(Module):
    """Multi-head self/cross attention with fused qkv projection.

    Input: tensor (N, T, D) for self-attention, or a (query, kv) tuple for
    cross-attention."""

    def __init__(self, embed_dim: int, num_heads: int,
                 causal: bool = False, with_bias: bool = True,
                 dropout: float = 0.0, shard: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.causal = causal
        self.with_bias = with_bias
        self.dropout = dropout
        # tensor parallelism (Megatron attention split): heads sharded over
        # the `model` mesh axis via qkv column / output row parallel specs
        self.shard = shard

    def param_specs(self):
        """Weights here are stored (in, out) and used as x @ W, so the
        output-dim split is dim 1 (vs dim 0 for Linear's (out, in))."""
        if not self.shard:
            return None
        from jax.sharding import PartitionSpec as P
        sp = {"wq": P(None, "model"), "wk": P(None, "model"),
              "wv": P(None, "model"), "wo": P("model", None)}
        if self.with_bias:
            sp.update({"bq": P("model"), "bk": P("model"),
                       "bv": P("model"), "bo": P()})
        return sp

    def init(self, rng):
        D = self.embed_dim
        ks = jax.random.split(rng, 4)
        xav = Xavier()
        params = {
            "wq": xav.init(ks[0], (D, D), D, D),
            "wk": xav.init(ks[1], (D, D), D, D),
            "wv": xav.init(ks[2], (D, D), D, D),
            "wo": xav.init(ks[3], (D, D), D, D),
        }
        if self.with_bias:
            for n in ("bq", "bk", "bv", "bo"):
                params[n] = jnp.zeros((D,), jnp.float32)
        return params, {}

    def _split(self, x):
        N, T, _ = x.shape
        return x.reshape(N, T, self.num_heads, self.head_dim) \
                .transpose(0, 2, 1, 3)

    def apply(self, params, state, input, *, training=False, rng=None):
        if isinstance(input, (tuple, list)):
            xq, xkv = input
        else:
            xq = xkv = input
        q = xq @ params["wq"]
        k = xkv @ params["wk"]
        v = xkv @ params["wv"]
        if self.with_bias:
            q, k, v = q + params["bq"], k + params["bk"], v + params["bv"]
        q, k, v = self._split(q), self._split(k), self._split(v)
        o = dot_product_attention(q, k, v, causal=self.causal)
        if self.dropout > 0 and training:
            if rng is None:
                raise ValueError("attention dropout needs an rng")
            keep = 1.0 - self.dropout
            o = jnp.where(jax.random.bernoulli(rng, keep, o.shape),
                          o / keep, 0.0)
        N, H, T, Dh = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(N, T, H * Dh)
        out = o @ params["wo"]
        if self.with_bias:
            out = out + params["bo"]
        return out, state
