"""Volumetric (3-D) layers.

Reference: ``DL/nn/VolumetricConvolution.scala``,
``VolumetricMaxPooling.scala``, ``VolumetricAveragePooling.scala``,
``VolumetricFullConvolution.scala`` — the video/3D family.  The reference
hand-writes vol2col + gemm loops; here each is one ``lax`` op that XLA
tiles onto the MXU.

Layout is NCDHW (batch, channel, time/depth, height, width), matching the
reference's (batch, plane, time, height, width).  Constructor argument
order follows the reference: kernel/stride/pad given as (T, W, H).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform

_DIMS = ("NCDHW", "OIDHW", "NCDHW")


class VolumetricConvolution(Module):
    """3-D convolution (reference ``VolumetricConvolution.scala``:
    vol2col + gemm → one ``lax.conv_general_dilated``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 bias_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()
        self.bias_init = bias_init or RandomUniform()

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        kt, kh, kw = self.kernel
        fan_in = self.n_input_plane * kt * kh * kw
        fan_out = self.n_output_plane * kt * kh * kw
        w_shape = (self.n_output_plane, self.n_input_plane, kt, kh, kw)
        params = {"weight": self.weight_init.init(k_w, w_shape,
                                                  fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init.init(
                k_b, (self.n_output_plane,), fan_in, fan_out)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        pt, ph, pw = self.pad
        y = lax.conv_general_dilated(
            input, params["weight"],
            window_strides=self.stride,
            padding=((pt, pt), (ph, ph), (pw, pw)),
            dimension_numbers=_DIMS)
        if self.with_bias:
            y = y + params["bias"][None, :, None, None, None]
        return y, state


class _VolPool(Module):
    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None,
                 d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)

    def _window(self):
        dims = (1, 1) + self.kernel
        strides = (1, 1) + self.stride
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in self.pad)
        return dims, strides, pads


class VolumetricMaxPooling(_VolPool):
    """3-D max pooling (reference ``VolumetricMaxPooling.scala``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        dims, strides, pads = self._window()
        y = lax.reduce_window(input, -jnp.inf, lax.max, dims, strides, pads)
        return y, state


class VolumetricAveragePooling(_VolPool):
    """3-D average pooling (reference ``VolumetricAveragePooling.scala``;
    countIncludePad=true semantics)."""

    def __init__(self, *args, count_include_pad: bool = True, **kw):
        super().__init__(*args, **kw)
        self.count_include_pad = count_include_pad

    def apply(self, params, state, input, *, training=False, rng=None):
        dims, strides, pads = self._window()
        summed = lax.reduce_window(input, 0.0, lax.add, dims, strides, pads)
        if self.count_include_pad:
            y = summed / float(jnp.prod(jnp.array(self.kernel)))
        else:
            counts = lax.reduce_window(jnp.ones_like(input), 0.0, lax.add,
                                       dims, strides, pads)
            y = summed / jnp.maximum(counts, 1.0)
        return y, state


class VolumetricFullConvolution(Module):
    """Transposed 3-D convolution (reference
    ``VolumetricFullConvolution.scala``); output size =
    (in-1)*stride - 2*pad + kernel + adj per spatial dim."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 adj_t: int = 0, adj_w: int = 0, adj_h: int = 0,
                 with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 bias_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.adj = (adj_t, adj_h, adj_w)
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()
        self.bias_init = bias_init or RandomUniform()

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        kt, kh, kw = self.kernel
        fan_in = self.n_input_plane * kt * kh * kw
        fan_out = self.n_output_plane * kt * kh * kw
        # IODHW like the reference's (input, output, kT, kH, kW)
        w_shape = (self.n_input_plane, self.n_output_plane, kt, kh, kw)
        params = {"weight": self.weight_init.init(k_w, w_shape,
                                                  fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init.init(
                k_b, (self.n_output_plane,), fan_in, fan_out)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        # fractionally-strided conv: dilate input by stride, convolve with
        # the flipped kernel (IODHW → OIDHW)
        w = jnp.transpose(jnp.flip(params["weight"], axis=(2, 3, 4)),
                          (1, 0, 2, 3, 4))
        pads = tuple(
            (k - 1 - p, k - 1 - p + a)
            for k, p, a in zip(self.kernel, self.pad, self.adj))
        y = lax.conv_general_dilated(
            input, w,
            window_strides=(1, 1, 1),
            padding=pads,
            lhs_dilation=self.stride,
            dimension_numbers=_DIMS)
        if self.with_bias:
            y = y + params["bias"][None, :, None, None, None]
        return y, state
