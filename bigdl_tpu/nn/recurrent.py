"""Recurrent stack.

Reference: ``DL/nn/Recurrent.scala`` (855 LoC) unrolls a ``Cell`` over time
with cloned cells sharing weights; ``RecurrentDecoder`` feeds output back as
input; plus ``RnnCell``/``LSTM``/``LSTMPeephole``/``GRU``/
``ConvLSTMPeephole``/``MultiRNNCell``/``BiRecurrent``/``TimeDistributed``.

TPU redesign: **unrolling becomes ``lax.scan``** — one compiled step body,
weights naturally shared, sequence dim handled by XLA (no cloned cells, no
hidden-state plumbing between mutable modules).  This is the SURVEY §7 risk
item "Recurrent/dynamic shapes under XLA": static max-length sequences +
masking, never data-dependent python loops.

Layout: batch-major ``(N, T, features)`` like the reference's default
(batchNormParams aside).  Cells are stateless modules whose ``apply`` takes
``(x_t, hidden)`` packed as a tuple and returns ``(out_t, new_hidden)``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.initialization import RandomUniform, InitializationMethod


def _cast_hidden(hidden, dtype):
    """Match the hidden state to the input dtype so bf16 mixed precision
    flows through the scan (an f32 hidden would promote every step's
    concat/matmul back to f32, silently disabling the MXU speedup)."""
    if not jnp.issubdtype(dtype, jnp.floating):
        return hidden
    return jax.tree_util.tree_map(lambda h: h.astype(dtype), hidden)


class Cell(Module):
    """Recurrent cell contract: ``step(params, x_t, hidden) -> (y_t, hidden)``
    plus ``initial_hidden(batch)``."""

    hidden_size: int

    def initial_hidden(self, batch_size: int):
        raise NotImplementedError

    def step(self, params, x_t, hidden):
        raise NotImplementedError

    # -- optional scan optimization (TPU) -------------------------------
    # The input-side projection x_t @ W_x has no sequential dependency,
    # so a cell may expose it for hoisting: ``Recurrent`` then computes
    # it for ALL timesteps as ONE large MXU-efficient matmul
    # ((T*N, D) @ (D, 4H)) and the scan body keeps only the h-side
    # matmul — roughly halving the work trapped inside the sequential
    # loop, which is where small-batch RNNs spend their time on TPU.
    # Numerics: x@Wx + h@Wh sums the D and H reduction axes separately
    # instead of as one (D+H) reduction — a reassociation within normal
    # float tolerance of the fused form.

    def hoist(self, params, xs):
        """Precompute the input projections for a (T, N, ...) sequence;
        return the per-step pytree to scan over, or None when this cell
        has no hoistable form (the default)."""
        return None

    def step_hoisted(self, params, zx_t, hidden):
        """``step`` consuming a :meth:`hoist` slice instead of x_t."""
        raise NotImplementedError

    # a Cell used standalone acts on one timestep: input=(x_t, hidden)
    def apply(self, params, state, input, *, training=False, rng=None):
        x_t, hidden = input
        y, new_hidden = self.step(params, x_t, hidden)
        return (y, new_hidden), state


def _uniform(rng, shape, fan_in):
    return RandomUniform().init(rng, shape, fan_in, fan_in)


class RnnCell(Cell):
    """Elman RNN: h' = act(W x + U h + b) (reference ``RNN.scala``
    RnnCell; default Tanh activation)."""

    def __init__(self, input_size: int, hidden_size: int,
                 activation=jnp.tanh, name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        fan = self.input_size + self.hidden_size
        return {"w_ih": _uniform(k1, (self.hidden_size, self.input_size), fan),
                "w_hh": _uniform(k2, (self.hidden_size, self.hidden_size), fan),
                "bias": _uniform(k3, (self.hidden_size,), fan)}, {}

    def initial_hidden(self, batch_size: int):
        return jnp.zeros((batch_size, self.hidden_size), jnp.float32)

    def step(self, params, x_t, h):
        h_new = self.activation(x_t @ params["w_ih"].T + h @ params["w_hh"].T
                                + params["bias"])
        return h_new, h_new

    def hoist(self, params, xs):
        return xs @ params["w_ih"].T + params["bias"]

    def step_hoisted(self, params, zx_t, h):
        h_new = self.activation(zx_t + h @ params["w_hh"].T)
        return h_new, h_new


class LSTM(Cell):
    """LSTM cell (reference ``LSTM.scala``): gates i,f,g,o from one fused
    projection of [x, h] — a single MXU matmul per step.

    ``impl`` selects the scan-body cell kernel for the hoisted path:
    ``None`` defers to ``Engine.kernel_impl()`` (``Config.kernel_impl``
    / ``BIGDL_TPU_KERNEL_IMPL``), ``"pallas"`` opts into the fused
    VMEM-resident cell (``ops/pallas_lstm.py`` — recurrent matmul with
    f32 accumulation + all four gates + cell/hidden update in one pass,
    replacing this chain of per-op HBM round-trips), ``"xla"`` pins the
    baseline lowering.  Unsupported shapes silently take the XLA path
    (``pallas_lstm.supported``); parity is gated in
    ``tests/test_pallas_kernels.py``."""

    def __init__(self, input_size: int, hidden_size: int,
                 forget_bias: float = 0.0, name: Optional[str] = None,
                 impl: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.forget_bias = forget_bias
        self.impl = impl

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        H, D = self.hidden_size, self.input_size
        fan = D + H
        w = _uniform(k1, (4 * H, D + H), fan)
        b = _uniform(k2, (4 * H,), fan)
        return {"weight": w, "bias": b}, {}

    def initial_hidden(self, batch_size: int):
        H = self.hidden_size
        return (jnp.zeros((batch_size, H), jnp.float32),
                jnp.zeros((batch_size, H), jnp.float32))

    def step(self, params, x_t, hidden):
        h, c = hidden
        z = jnp.concatenate([x_t, h], axis=-1) @ params["weight"].T \
            + params["bias"]
        return self._gates(z, c)

    def _gates(self, z, c):
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f + self.forget_bias)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)

    def hoist(self, params, xs):
        D = self.input_size
        return xs @ params["weight"][:, :D].T + params["bias"]

    def step_hoisted(self, params, zx_t, hidden):
        h, c = hidden
        if self._fused_cell_engaged(h):
            from bigdl_tpu.ops.pallas_lstm import lstm_cell
            # (H, 4H) transposed recurrent slice; loop-invariant, so
            # XLA hoists the transpose out of the scan
            w_t = params["weight"][:, self.input_size:].T
            h_new, c_new = lstm_cell(zx_t, h, c, w_t,
                                     forget_bias=self.forget_bias)
            return h_new, (h_new, c_new)
        # the loop-invariant W_h slice is hoisted out of the scan by
        # XLA's while-loop invariant code motion
        z = zx_t + h @ params["weight"][:, self.input_size:].T
        return self._gates(z, c)

    def _fused_cell_engaged(self, h) -> bool:
        """Static (trace-time) kernel choice: resolved impl says pallas
        AND the measured supported() gate passes for this shape/dtype —
        anything else silently keeps the XLA chain."""
        from bigdl_tpu.ops import pallas_lstm, resolve_kernel_impl
        if resolve_kernel_impl(self.impl) != "pallas":
            return False
        return pallas_lstm.supported(h.shape[0], self.hidden_size,
                                     h.dtype.type)


class LSTMPeephole(Cell):
    """LSTM with peephole connections (reference ``LSTMPeephole.scala``)."""

    def __init__(self, input_size: int, hidden_size: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        H, D = self.hidden_size, self.input_size
        fan = D + H
        return {"weight": _uniform(k1, (4 * H, D + H), fan),
                "bias": _uniform(k2, (4 * H,), fan),
                "peep": _uniform(k3, (3, H), fan)}, {}

    def initial_hidden(self, batch_size: int):
        H = self.hidden_size
        return (jnp.zeros((batch_size, H), jnp.float32),
                jnp.zeros((batch_size, H), jnp.float32))

    def step(self, params, x_t, hidden):
        h, c = hidden
        z = jnp.concatenate([x_t, h], axis=-1) @ params["weight"].T \
            + params["bias"]
        i, f, g, o = jnp.split(z, 4, axis=-1)
        p = params["peep"]
        i = jax.nn.sigmoid(i + p[0] * c)
        f = jax.nn.sigmoid(f + p[1] * c)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        o = jax.nn.sigmoid(o + p[2] * c_new)
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class GRU(Cell):
    """GRU cell (reference ``GRU.scala``)."""

    def __init__(self, input_size: int, hidden_size: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size

    def init(self, rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        H, D = self.hidden_size, self.input_size
        fan = D + H
        return {"w_gates": _uniform(k1, (2 * H, D + H), fan),
                "b_gates": _uniform(k2, (2 * H,), fan),
                "w_cand": _uniform(k3, (H, D + H), fan),
                "b_cand": _uniform(k4, (H,), fan)}, {}

    def initial_hidden(self, batch_size: int):
        return jnp.zeros((batch_size, self.hidden_size), jnp.float32)

    def step(self, params, x_t, h):
        z = jnp.concatenate([x_t, h], axis=-1) @ params["w_gates"].T \
            + params["b_gates"]
        r, u = jnp.split(jax.nn.sigmoid(z), 2, axis=-1)
        cand = jnp.tanh(jnp.concatenate([x_t, r * h], axis=-1)
                        @ params["w_cand"].T + params["b_cand"])
        h_new = u * h + (1 - u) * cand
        return h_new, h_new

    def hoist(self, params, xs):
        D = self.input_size
        return (xs @ params["w_gates"][:, :D].T + params["b_gates"],
                xs @ params["w_cand"][:, :D].T + params["b_cand"])

    def step_hoisted(self, params, zx_t, h):
        zg, zc = zx_t
        D = self.input_size
        z = zg + h @ params["w_gates"][:, D:].T
        r, u = jnp.split(jax.nn.sigmoid(z), 2, axis=-1)
        cand = jnp.tanh(zc + (r * h) @ params["w_cand"][:, D:].T)
        h_new = u * h + (1 - u) * cand
        return h_new, h_new


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM over NCHW feature maps (reference
    ``ConvLSTMPeephole.scala``).

    ``with_peephole=True`` adds the reference's per-channel peephole
    terms (Wci/Wcf/Wco elementwise on the cell state) and is the
    default, matching the reference's ``withPeephole=true``;
    ``False`` is the plain ConvLSTM variant (its
    ``withPeephole=false`` mode)."""

    def __init__(self, input_size: int, output_size: int, kernel: int = 3,
                 spatial: Optional[tuple[int, int]] = None,
                 with_peephole: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.output_size = input_size, output_size
        self.kernel = kernel
        self.spatial = spatial  # (H, W), required for initial_hidden
        self.hidden_size = output_size
        self.with_peephole = with_peephole

    def init(self, rng):
        # split(2) when peephole-free so earlier rounds' seeded init
        # streams are preserved exactly
        if self.with_peephole:
            k1, k2, k3 = jax.random.split(rng, 3)
        else:
            k1, k2 = jax.random.split(rng)
        C_in, C_out, K = self.input_size, self.output_size, self.kernel
        fan = (C_in + C_out) * K * K
        w = _uniform(k1, (4 * C_out, C_in + C_out, K, K), fan)
        b = _uniform(k2, (4 * C_out,), fan)
        params = {"weight": w, "bias": b}
        if self.with_peephole:
            # per-channel Wci/Wcf/Wco (reference peephole CMuls)
            params["peep"] = _uniform(k3, (3, C_out), fan)
        return params, {}

    def initial_hidden(self, batch_size: int):
        assert self.spatial is not None, \
            "ConvLSTMPeephole needs spatial=(H, W) for initial hidden"
        H, W = self.spatial
        shape = (batch_size, self.output_size, H, W)
        return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))

    def step(self, params, x_t, hidden):
        if self.with_peephole and "peep" not in params:
            raise KeyError(
                "ConvLSTMPeephole now defaults to with_peephole=True "
                "(the reference default); these params have no 'peep' "
                "entry — construct with with_peephole=False to restore "
                "a peephole-free checkpoint")
        h, c = hidden
        z = lax.conv_general_dilated(
            jnp.concatenate([x_t, h], axis=1), params["weight"],
            window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        z = z + params["bias"][None, :, None, None]
        i, f, g, o = jnp.split(z, 4, axis=1)
        if self.with_peephole:
            p = params["peep"][:, None, :, None, None]
            i = i + p[0] * c
            f = f + p[1] * c
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        if self.with_peephole:
            o = o + params["peep"][2][None, :, None, None] * c_new
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class ConvLSTMPeephole3D(Cell):
    """Volumetric ConvLSTM over NCDHW feature maps (reference
    ``ConvLSTMPeephole3D.scala``; 3-D twin of :class:`ConvLSTMPeephole`,
    including the ``withPeephole=true`` reference default)."""

    def __init__(self, input_size: int, output_size: int, kernel: int = 3,
                 spatial: Optional[tuple[int, int, int]] = None,
                 with_peephole: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.output_size = input_size, output_size
        self.kernel = kernel
        self.spatial = spatial  # (D, H, W), required for initial_hidden
        self.hidden_size = output_size
        self.with_peephole = with_peephole

    def init(self, rng):
        if self.with_peephole:
            k1, k2, k3 = jax.random.split(rng, 3)
        else:
            k1, k2 = jax.random.split(rng)
        C_in, C_out, K = self.input_size, self.output_size, self.kernel
        fan = (C_in + C_out) * K * K * K
        w = _uniform(k1, (4 * C_out, C_in + C_out, K, K, K), fan)
        b = _uniform(k2, (4 * C_out,), fan)
        params = {"weight": w, "bias": b}
        if self.with_peephole:
            params["peep"] = _uniform(k3, (3, C_out), fan)
        return params, {}

    def initial_hidden(self, batch_size: int):
        assert self.spatial is not None, \
            "ConvLSTMPeephole3D needs spatial=(D, H, W) for initial hidden"
        D, H, W = self.spatial
        shape = (batch_size, self.output_size, D, H, W)
        return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))

    def step(self, params, x_t, hidden):
        if self.with_peephole and "peep" not in params:
            raise KeyError(
                "ConvLSTMPeephole3D now defaults to with_peephole=True "
                "(the reference default); these params have no 'peep' "
                "entry — construct with with_peephole=False to restore "
                "a peephole-free checkpoint")
        h, c = hidden
        z = lax.conv_general_dilated(
            jnp.concatenate([x_t, h], axis=1), params["weight"],
            window_strides=(1, 1, 1), padding="SAME",
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        z = z + params["bias"][None, :, None, None, None]
        i, f, g, o = jnp.split(z, 4, axis=1)
        if self.with_peephole:
            p = params["peep"][:, None, :, None, None, None]
            i = i + p[0] * c
            f = f + p[1] * c
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        if self.with_peephole:
            o = o + params["peep"][2][None, :, None, None, None] * c_new
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class MultiRNNCell(Cell):
    """Stack cells vertically (reference ``MultiRNNCell.scala``)."""

    def __init__(self, cells: Sequence[Cell], name: Optional[str] = None):
        super().__init__(name)
        self.cells = list(cells)
        self.hidden_size = self.cells[-1].hidden_size

    def spec_children(self):
        return {str(i): c for i, c in enumerate(self.cells)}

    def init(self, rng):
        params = {}
        for i, c in enumerate(self.cells):
            rng, sub = jax.random.split(rng)
            p, _ = c.init(sub)
            params[str(i)] = p
        return params, {}

    def initial_hidden(self, batch_size: int):
        return tuple(c.initial_hidden(batch_size) for c in self.cells)

    def step(self, params, x_t, hidden):
        new_hidden = []
        out = x_t
        for i, c in enumerate(self.cells):
            out, h = c.step(params[str(i)], out, hidden[i])
            new_hidden.append(h)
        return out, tuple(new_hidden)

    def hoist(self, params, xs):
        # only layer 0 sees the raw sequence; deeper layers consume
        # in-loop outputs, so their projections cannot move out.
        # getattr: layer 0 may be a duck-typed/quantized cell without
        # the hoist API (same contract as Recurrent.apply)
        h0 = getattr(self.cells[0], "hoist", None)
        return h0(params["0"], xs) if h0 is not None else None

    def step_hoisted(self, params, zx_t, hidden):
        new_hidden = []
        out, h = self.cells[0].step_hoisted(params["0"], zx_t, hidden[0])
        new_hidden.append(h)
        for i, c in enumerate(self.cells[1:], start=1):
            out, h = c.step(params[str(i)], out, hidden[i])
            new_hidden.append(h)
        return out, tuple(new_hidden)


class Recurrent(Module):
    """Run a Cell over the time dim of (N, T, ...) via ``lax.scan``
    (reference ``Recurrent.scala``; returns the full output sequence).

    TPU scan discipline: the input-side projections are hoisted out of
    the loop when the cell supports it (see :meth:`Cell.hoist` — one
    large MXU matmul replaces T small ones), and ``unroll`` is passed to
    ``lax.scan`` — small-batch RNN steps are dispatch-bound on TPU, so
    unrolling the loop body amortizes per-iteration overhead (measured
    on the PTB bench; see bench.py).  Both are exact-math
    transformations (hoisting reassociates one float reduction)."""

    def __init__(self, cell: Cell, reverse: bool = False,
                 unroll: int = 1, name: Optional[str] = None):
        super().__init__(name)
        self.cell = cell
        self.reverse = reverse
        self.unroll = unroll

    def spec_children(self):
        return self.cell

    def init(self, rng):
        return self.cell.init(rng)

    def apply(self, params, state, input, *, training=False, rng=None):
        N = input.shape[0]
        hidden0 = _cast_hidden(self.cell.initial_hidden(N), input.dtype)
        xs = jnp.moveaxis(input, 1, 0)  # (T, N, ...) scan-major
        if self.reverse:
            xs = jnp.flip(xs, axis=0)

        # duck-typed: any object with step/initial_hidden is a valid
        # cell (quantized cells, user cells predating the hoist API)
        hoist = getattr(self.cell, "hoist", None)
        zx = hoist(params, xs) if hoist is not None else None
        if zx is not None:
            def body(hidden, zx_t):
                y, new_hidden = self.cell.step_hoisted(params, zx_t,
                                                       hidden)
                return new_hidden, y
            _, ys = lax.scan(body, hidden0, zx, unroll=self.unroll)
        else:
            def body(hidden, x_t):
                y, new_hidden = self.cell.step(params, x_t, hidden)
                return new_hidden, y
            _, ys = lax.scan(body, hidden0, xs, unroll=self.unroll)
        if self.reverse:
            ys = jnp.flip(ys, axis=0)
        return jnp.moveaxis(ys, 0, 1), state  # back to (N, T, ...)


class BiRecurrent(Module):
    """Bidirectional wrapper (reference ``BiRecurrent.scala``; merge =
    concat on the feature dim by default, or 'add')."""

    def __init__(self, cell_fwd: Cell, cell_bwd: Optional[Cell] = None,
                 merge: str = "concat", name: Optional[str] = None):
        super().__init__(name)
        import copy
        self.fwd = Recurrent(cell_fwd)
        self.bwd = Recurrent(cell_bwd if cell_bwd is not None
                             else copy.deepcopy(cell_fwd), reverse=True)
        self.merge = merge

    def spec_children(self):
        return {"fwd": self.fwd, "bwd": self.bwd}

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        pf, _ = self.fwd.init(k1)
        pb, _ = self.bwd.init(k2)
        return {"fwd": pf, "bwd": pb}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        yf, _ = self.fwd.apply(params["fwd"], {}, input, training=training)
        yb, _ = self.bwd.apply(params["bwd"], {}, input, training=training)
        if self.merge == "concat":
            return jnp.concatenate([yf, yb], axis=-1), state
        return yf + yb, state


class RecurrentDecoder(Module):
    """Decode ``seq_length`` steps feeding each output back as the next
    input (reference ``RecurrentDecoder.scala``).  Input: the first-step
    input (N, features)."""

    def __init__(self, cell: Cell, seq_length: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.cell = cell
        self.seq_length = seq_length

    def spec_children(self):
        return self.cell

    def init(self, rng):
        return self.cell.init(rng)

    def apply(self, params, state, input, *, training=False, rng=None):
        N = input.shape[0]
        hidden0 = _cast_hidden(self.cell.initial_hidden(N), input.dtype)

        def body(carry, _):
            x, hidden = carry
            y, new_hidden = self.cell.step(params, x, hidden)
            return (y, new_hidden), y

        _, ys = lax.scan(body, (input, hidden0), None,
                         length=self.seq_length)
        return jnp.moveaxis(ys, 0, 1), state


class TimeDistributed(Module):
    """Apply an inner module independently at each timestep of (N, T, ...)
    (reference ``TimeDistributed.scala``) by folding time into batch —
    XLA sees one big batched op instead of T small ones."""

    def __init__(self, layer: Module, name: Optional[str] = None):
        super().__init__(name)
        self.layer = layer

    def spec_children(self):
        return self.layer

    def init(self, rng):
        return self.layer.init(rng)

    def apply(self, params, state, input, *, training=False, rng=None):
        N, T = input.shape[0], input.shape[1]
        flat = input.reshape((N * T,) + input.shape[2:])
        out, new_state = self.layer.apply(params, state, flat,
                                          training=training, rng=rng)
        return out.reshape((N, T) + out.shape[1:]), new_state
