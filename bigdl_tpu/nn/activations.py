"""Activation layers (reference: ~30 activation files at ``DL/nn/`` —
``ReLU.scala``, ``Tanh.scala``, ``Sigmoid.scala``, ``ELU.scala``,
``PReLU.scala``, ``RReLU.scala``, ``SReLU.scala``, …).

All stateless ones are one jnp expression; XLA fuses them into the
surrounding matmul/conv, which replaces the reference's MKL-DNN fusion pass
(``nn/mkldnn/DnnBase.scala:302-333``) with zero framework code.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class _Stateless(Module):
    def _fn(self, x):
        raise NotImplementedError

    def apply(self, params, state, input, *, training=False, rng=None):
        return self._fn(input), state


class ReLU(_Stateless):
    def _fn(self, x):
        return jax.nn.relu(x)


class ReLU6(_Stateless):
    def _fn(self, x):
        return jnp.clip(x, 0.0, 6.0)


class Tanh(_Stateless):
    def _fn(self, x):
        return jnp.tanh(x)


class Sigmoid(_Stateless):
    def _fn(self, x):
        return jax.nn.sigmoid(x)


class SoftMax(_Stateless):
    def _fn(self, x):
        return jax.nn.softmax(x, axis=-1)


class LogSoftMax(_Stateless):
    def _fn(self, x):
        return jax.nn.log_softmax(x, axis=-1)


class SoftPlus(_Stateless):
    def __init__(self, beta: float = 1.0, name=None):
        super().__init__(name)
        self.beta = beta

    def _fn(self, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(_Stateless):
    def _fn(self, x):
        return x / (1.0 + jnp.abs(x))


class ELU(_Stateless):
    def __init__(self, alpha: float = 1.0, inplace: bool = False, name=None):
        super().__init__(name)
        self.alpha = alpha

    def _fn(self, x):
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(x))


class LeakyReLU(_Stateless):
    def __init__(self, negval: float = 0.01, name=None):
        super().__init__(name)
        self.negval = negval

    def _fn(self, x):
        return jnp.where(x >= 0, x, self.negval * x)


class HardTanh(_Stateless):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 name=None):
        super().__init__(name)
        self.min_value, self.max_value = min_value, max_value

    def _fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class HardSigmoid(_Stateless):
    def _fn(self, x):
        return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class GELU(_Stateless):
    """Not in the reference (pre-transformer era) — provided because the
    TPU build treats attention models as first-class."""

    def _fn(self, x):
        return jax.nn.gelu(x)


class SiLU(_Stateless):
    def _fn(self, x):
        return jax.nn.silu(x)


class PReLU(Module):
    """Learnable leaky slope (reference ``PReLU.scala``; nOutputPlane=0
    means one shared slope)."""

    def __init__(self, n_output_plane: int = 0, name=None):
        super().__init__(name)
        self.n_output_plane = n_output_plane

    def init(self, rng):
        n = max(self.n_output_plane, 1)
        return {"weight": jnp.full((n,), 0.25, jnp.float32)}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        w = params["weight"]
        if self.n_output_plane > 0 and input.ndim == 4:
            w = w[None, :, None, None]
        return jnp.where(input >= 0, input, w * input), state


class RReLU(Module):
    """Randomized leaky ReLU (reference ``RReLU.scala``): slope ~
    U(lower, upper) in training, fixed mean slope in eval."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 name=None):
        super().__init__(name)
        self.lower, self.upper = lower, upper

    def apply(self, params, state, input, *, training=False, rng=None):
        if training:
            if rng is None:
                raise ValueError("RReLU in training mode needs an rng")
            a = jax.random.uniform(rng, input.shape, input.dtype,
                                   self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(input >= 0, input, a * input), state


class SReLU(Module):
    """S-shaped ReLU with 4 learnable params per channel
    (reference ``SReLU.scala``)."""

    def __init__(self, shape: Sequence[int], name=None):
        super().__init__(name)
        self.shape = tuple(shape)

    def init(self, rng):
        return {"t_left": jnp.zeros(self.shape, jnp.float32),
                "a_left": jnp.zeros(self.shape, jnp.float32),
                "t_right": jnp.ones(self.shape, jnp.float32),
                "a_right": jnp.ones(self.shape, jnp.float32)}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y = jnp.where(input >= tr, tr + ar * (input - tr),
                      jnp.where(input <= tl, tl + al * (input - tl), input))
        return y, state


class Threshold(_Stateless):
    """(reference ``Threshold.scala``) x if x > th else val."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, name=None):
        super().__init__(name)
        self.th, self.v = th, v

    def _fn(self, x):
        return jnp.where(x > self.th, x, self.v)


class HardShrink(_Stateless):
    """(reference ``HardShrink.scala``) 0 inside [-λ, λ], identity outside."""

    def __init__(self, the_lambda: float = 0.5, name=None):
        super().__init__(name)
        self.the_lambda = the_lambda

    def _fn(self, x):
        return jnp.where(jnp.abs(x) > self.the_lambda, x, 0.0)


class SoftShrink(_Stateless):
    """(reference ``SoftShrink.scala``) shrink magnitudes by λ, 0 inside."""

    def __init__(self, the_lambda: float = 0.5, name=None):
        super().__init__(name)
        self.the_lambda = the_lambda

    def _fn(self, x):
        lam = self.the_lambda
        return jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0))


class LogSigmoid(_Stateless):
    """(reference ``LogSigmoid.scala``) log(1/(1+e^-x))."""

    def _fn(self, x):
        return jax.nn.log_sigmoid(x)


class SoftMin(_Stateless):
    """(reference ``SoftMin.scala``) softmax of -x over the last dim."""

    def _fn(self, x):
        return jax.nn.softmax(-x, axis=-1)


class TanhShrink(_Stateless):
    """(reference ``TanhShrink.scala``) x - tanh(x)."""

    def _fn(self, x):
        return x - jnp.tanh(x)
