"""Table-op, distance/similarity, and stochastic-regularization layers.

Reference files (``DL/nn/``): ``MM.scala``, ``MV.scala``,
``DotProduct.scala``, ``CrossProduct.scala``, ``PairwiseDistance.scala``,
``CosineDistance.scala``, ``Bilinear.scala``, ``Cosine.scala``,
``Euclidean.scala``, ``Add.scala``, ``Mul.scala``, ``Maxout.scala``,
``Highway.scala``, ``MixtureTable.scala``, ``MaskedSelect.scala``,
``Reverse.scala``, ``Tile.scala``, ``Negative.scala``,
``InferReshape.scala``, ``NarrowTable.scala``, ``CAveTable.scala``,
``BifurcateSplitTable.scala``, ``GradientReversal.scala``,
``GaussianDropout.scala``, ``GaussianNoise.scala``,
``GaussianSampler.scala``, ``L1Penalty.scala``,
``NegativeEntropyPenalty.scala``, ``ActivityRegularization.scala``,
``BinaryThreshold.scala``, ``Bottle.scala``, ``MapTable.scala``,
``CrossProduct.scala``.

Tables are Python tuples/lists (pytrees).  Penalty layers (L1Penalty &
co) diverge from the reference's mutable ``loss`` field: they are
identity in ``apply`` and expose ``penalty(input)`` — add it to the
criterion (the functional equivalent of the reference adding the penalty
during ``updateOutput``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform


# ------------------------------------------------------------- table math
class MM(Module):
    """Batched matmul of a 2-table (reference ``MM.scala``; transA/B)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False,
                 name=None):
        super().__init__(name)
        self.trans_a = trans_a
        self.trans_b = trans_b

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b), state


class MV(Module):
    """Batched matrix×vector (reference ``MV.scala``)."""

    def __init__(self, trans: bool = False, name=None):
        super().__init__(name)
        self.trans = trans

    def apply(self, params, state, input, *, training=False, rng=None):
        m, v = input
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), state


class DotProduct(Module):
    """Row-wise dot product of two inputs (reference ``DotProduct.scala``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        return jnp.sum(a * b, axis=-1), state


class CrossProduct(Module):
    """All pairwise dot products between table entries (reference
    ``CrossProduct.scala``; Deep&Cross-style feature crossing).
    Output (N, K*(K-1)/2) in (i<j) order."""

    def apply(self, params, state, input, *, training=False, rng=None):
        outs = []
        for i in range(len(input)):
            for j in range(i + 1, len(input)):
                outs.append(jnp.sum(input[i] * input[j], axis=-1))
        return jnp.stack(outs, axis=-1), state


class PairwiseDistance(Module):
    """p-norm distance between two inputs (reference
    ``PairwiseDistance.scala``)."""

    def __init__(self, norm: int = 2, name=None):
        super().__init__(name)
        self.norm = norm

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        d = jnp.sum(jnp.abs(a - b) ** self.norm, axis=-1) \
            ** (1.0 / self.norm)
        return d, state


class CosineDistance(Module):
    """Cosine similarity of two inputs (reference ``CosineDistance.scala``
    — despite the name it outputs similarity, like Torch)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        a, b = input
        eps = 1e-12
        na = jnp.maximum(jnp.linalg.norm(a, axis=-1), eps)
        nb = jnp.maximum(jnp.linalg.norm(b, axis=-1), eps)
        return jnp.sum(a * b, axis=-1) / (na * nb), state


# --------------------------------------------------- parameterized distances
class Bilinear(Module):
    """y_o = x1ᵀ W_o x2 + b_o over a 2-table (reference ``Bilinear.scala``)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 name=None):
        super().__init__(name)
        self.in1, self.in2, self.out = input_size1, input_size2, output_size
        self.bias_res = bias_res
        self.weight_init = weight_init or RandomUniform()

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.in1 * self.in2
        params = {"weight": self.weight_init.init(
            k_w, (self.out, self.in1, self.in2), fan_in, self.out)}
        if self.bias_res:
            params["bias"] = jnp.zeros((self.out,), jnp.float32)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        x1, x2 = input
        y = jnp.einsum("ni,oij,nj->no", x1, params["weight"], x2)
        if self.bias_res:
            y = y + params["bias"]
        return y, state


class Cosine(Module):
    """Cosine similarity against each weight row (reference
    ``Cosine.scala``)."""

    def __init__(self, input_size: int, output_size: int,
                 weight_init: Optional[InitializationMethod] = None,
                 name=None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.weight_init = weight_init or RandomUniform()

    def init(self, rng):
        w = self.weight_init.init(rng, (self.output_size, self.input_size),
                                  self.input_size, self.output_size)
        return {"weight": w}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        w = params["weight"]
        eps = 1e-12
        xn = jnp.maximum(jnp.linalg.norm(input, axis=-1, keepdims=True), eps)
        wn = jnp.maximum(jnp.linalg.norm(w, axis=-1), eps)
        return (input @ w.T) / xn / wn, state


class Euclidean(Module):
    """L2 distance to each weight column (reference ``Euclidean.scala``)."""

    def __init__(self, input_size: int, output_size: int,
                 weight_init: Optional[InitializationMethod] = None,
                 name=None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.weight_init = weight_init or RandomUniform()

    def init(self, rng):
        w = self.weight_init.init(rng, (self.output_size, self.input_size),
                                  self.input_size, self.output_size)
        return {"weight": w}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        diff = input[:, None, :] - params["weight"][None]
        return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, -1), 1e-24)), state


class Add(Module):
    """Learnable bias add (reference ``Add.scala``)."""

    def __init__(self, input_size: int, name=None):
        super().__init__(name)
        self.input_size = input_size

    def init(self, rng):
        return {"bias": jnp.zeros((self.input_size,), jnp.float32)}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        return input + params["bias"], state


class Mul(Module):
    """Single learnable scalar gain (reference ``Mul.scala``)."""

    def init(self, rng):
        return {"weight": jnp.ones((), jnp.float32)}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        return input * params["weight"], state


class Maxout(Module):
    """Linear with ``pool`` pieces, max over pieces (reference
    ``Maxout.scala``)."""

    def __init__(self, input_size: int, output_size: int, pool: int,
                 with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 name=None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.pool = pool
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        params = {"weight": self.weight_init.init(
            k_w, (self.pool * self.output_size, self.input_size),
            self.input_size, self.output_size)}
        if self.with_bias:
            params["bias"] = jnp.zeros(
                (self.pool * self.output_size,), jnp.float32)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        y = input @ params["weight"].T
        if self.with_bias:
            y = y + params["bias"]
        y = y.reshape(y.shape[0], self.pool, self.output_size)
        return jnp.max(y, axis=1), state


class Highway(Module):
    """Highway network block: t·g(Wx) + (1-t)·x (reference
    ``Highway.scala``; t = sigmoid gate, g default tanh)."""

    def __init__(self, size: int, with_bias: bool = True, activation=None,
                 weight_init: Optional[InitializationMethod] = None,
                 name=None):
        super().__init__(name)
        self.size = size
        self.with_bias = with_bias
        self.activation = activation or jnp.tanh
        self.weight_init = weight_init or RandomUniform()

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        params = {
            "gate_weight": self.weight_init.init(
                k1, (self.size, self.size), self.size, self.size),
            "weight": self.weight_init.init(
                k2, (self.size, self.size), self.size, self.size),
        }
        if self.with_bias:
            # gate bias init negative like common practice? reference uses
            # zeros — match the reference
            params["gate_bias"] = jnp.zeros((self.size,), jnp.float32)
            params["bias"] = jnp.zeros((self.size,), jnp.float32)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        t = input @ params["gate_weight"].T
        h = input @ params["weight"].T
        if self.with_bias:
            t = t + params["gate_bias"]
            h = h + params["bias"]
        t = jax.nn.sigmoid(t)
        return t * self.activation(h) + (1 - t) * input, state


# ------------------------------------------------------------ table utils
class MixtureTable(Module):
    """Mixture-of-experts blend: (gater (N,K), experts) → Σ g_k·e_k
    (reference ``MixtureTable.scala``).  Experts: K-tuple of (N, ...)
    tensors or one (N, K, ...) tensor."""

    def apply(self, params, state, input, *, training=False, rng=None):
        gater, experts = input
        if isinstance(experts, (list, tuple)):
            experts = jnp.stack(experts, axis=1)
        g = gater.reshape(gater.shape + (1,) * (experts.ndim - 2))
        return jnp.sum(g * experts, axis=1), state


class MaskedSelect(Module):
    """Select elements where mask≠0 (reference ``MaskedSelect.scala``).

    DYNAMIC output shape — usable eagerly / on host, NOT under jit (XLA
    requires static shapes; the reference's use sites are host-side too)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        x, mask = input
        return x[mask.astype(bool)], state


class Reverse(Module):
    """Flip along a dim (reference ``Reverse.scala``; dim 0-based here)."""

    def __init__(self, dim: int = 0, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.flip(input, axis=self.dim), state


class Tile(Module):
    """Repeat ``copies`` times along ``dim`` (reference ``Tile.scala``)."""

    def __init__(self, dim: int = 0, copies: int = 2, name=None):
        super().__init__(name)
        self.dim = dim
        self.copies = copies

    def apply(self, params, state, input, *, training=False, rng=None):
        reps = [1] * input.ndim
        reps[self.dim] = self.copies
        return jnp.tile(input, reps), state


class Negative(Module):
    """y = -x (reference ``Negative.scala``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return -input, state


class InferReshape(Module):
    """Reshape with -1 inference and 0 = copy-input-dim (reference
    ``InferReshape.scala``)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False,
                 name=None):
        super().__init__(name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, state, input, *, training=False, rng=None):
        in_shape = input.shape[1:] if self.batch_mode else input.shape
        out = []
        for i, s in enumerate(self.size):
            out.append(in_shape[i] if s == 0 else s)
        if self.batch_mode:
            out = [input.shape[0]] + out
        return input.reshape(tuple(out)), state


class NarrowTable(Module):
    """Slice a table (reference ``NarrowTable.scala``; offset 0-based)."""

    def __init__(self, offset: int, length: int = 1, name=None):
        super().__init__(name)
        self.offset = offset
        self.length = length

    def apply(self, params, state, input, *, training=False, rng=None):
        out = tuple(input[self.offset:self.offset + self.length])
        return out[0] if self.length == 1 else out, state


class CAveTable(Module):
    """Elementwise average of table entries (reference ``CAveTable.scala``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return sum(input) / len(input), state


class BifurcateSplitTable(Module):
    """Split a tensor in half along ``dim`` into a 2-table (reference
    ``BifurcateSplitTable.scala``)."""

    def __init__(self, dim: int, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        half = input.shape[self.dim] // 2
        a = jax.lax.slice_in_dim(input, 0, half, axis=self.dim)
        b = jax.lax.slice_in_dim(input, half, input.shape[self.dim],
                                 axis=self.dim)
        return (a, b), state


class Bottle(Module):
    """Flatten leading dims, apply inner module, unflatten (reference
    ``Bottle.scala``; n_input_dims=2 semantics: (N, T, C) → (N*T, C))."""

    def __init__(self, module: Module, n_input_dims: int = 2, name=None):
        super().__init__(name)
        self.module = module
        self.n_input_dims = n_input_dims

    def init(self, rng):
        return self.module.init(rng)

    def apply(self, params, state, input, *, training=False, rng=None):
        lead = input.shape[:-(self.n_input_dims - 1)] \
            if self.n_input_dims > 1 else input.shape
        flat = input.reshape((-1,) + input.shape[len(lead):])
        y, new_state = self.module.apply(params, state, flat,
                                         training=training, rng=rng)
        return y.reshape(lead + y.shape[1:]), new_state


class MapTable(Module):
    """Apply one module (shared weights) to every table entry (reference
    ``MapTable.scala``)."""

    def __init__(self, module: Module, name=None):
        super().__init__(name)
        self.module = module

    def init(self, rng):
        return self.module.init(rng)

    def apply(self, params, state, input, *, training=False, rng=None):
        outs = []
        new_state = state
        for i, x in enumerate(input):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, new_state = self.module.apply(params, new_state, x,
                                             training=training, rng=r)
            outs.append(y)
        return tuple(outs), new_state


# --------------------------------------------------- gradient / stochastic
class GradientReversal(Module):
    """Identity forward, -λ·grad backward (reference
    ``GradientReversal.scala``; domain-adversarial training)."""

    def __init__(self, the_lambda: float = 1.0, name=None):
        super().__init__(name)
        self.the_lambda = the_lambda

        @jax.custom_vjp
        def _rev(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, g):
            return (-self.the_lambda * g,)

        _rev.defvjp(fwd, bwd)
        self._rev = _rev

    def apply(self, params, state, input, *, training=False, rng=None):
        return self._rev(input), state


class GaussianDropout(Module):
    """Multiplicative N(1, p/(1-p)) noise in training (reference
    ``GaussianDropout.scala``)."""

    def __init__(self, rate: float, name=None):
        super().__init__(name)
        assert 0.0 <= rate < 1.0
        self.rate = rate

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.rate == 0.0:
            return input, state
        if rng is None:
            raise ValueError(f"{self.name}: training needs rng")
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + std * jax.random.normal(rng, input.shape, input.dtype)
        return input * noise, state


class GaussianNoise(Module):
    """Additive N(0, σ) noise in training (reference
    ``GaussianNoise.scala``)."""

    def __init__(self, stddev: float, name=None):
        super().__init__(name)
        self.stddev = stddev

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training:
            return input, state
        if rng is None:
            raise ValueError(f"{self.name}: training needs rng")
        return input + self.stddev * jax.random.normal(
            rng, input.shape, input.dtype), state


class GaussianSampler(Module):
    """VAE reparameterization: (mean, log_var) → mean + exp(lv/2)·ε
    (reference ``GaussianSampler.scala``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        mean, log_var = input
        if rng is None:
            raise ValueError(f"{self.name}: needs rng")
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(log_var * 0.5) * eps, state


# ------------------------------------------------------- penalty layers
class L1Penalty(Module):
    """Identity with an L1 activity penalty (reference
    ``L1Penalty.scala``); add ``penalty(x)`` to the loss."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 name=None):
        super().__init__(name)
        self.l1weight = l1weight
        self.size_average = size_average

    def penalty(self, input):
        p = self.l1weight * jnp.sum(jnp.abs(input))
        return p / input.shape[0] if self.size_average else p

    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state


class NegativeEntropyPenalty(Module):
    """Identity with a -H(p) penalty encouraging diversity (reference
    ``NegativeEntropyPenalty.scala``)."""

    def __init__(self, beta: float = 0.01, name=None):
        super().__init__(name)
        self.beta = beta

    def penalty(self, input):
        return self.beta * jnp.sum(input * jnp.log(input + 1e-12))

    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state


class ActivityRegularization(Module):
    """Identity with L1+L2 activity penalties (reference
    ``ActivityRegularization.scala``)."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0, name=None):
        super().__init__(name)
        self.l1 = l1
        self.l2 = l2

    def penalty(self, input):
        return (self.l1 * jnp.sum(jnp.abs(input))
                + self.l2 * jnp.sum(input * input))

    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state


class BinaryThreshold(Module):
    """x > th → 1 else 0 (reference ``BinaryThreshold.scala``)."""

    def __init__(self, th: float = 1e-6, name=None):
        super().__init__(name)
        self.th = th

    def apply(self, params, state, input, *, training=False, rng=None):
        return (input > self.th).astype(input.dtype), state
