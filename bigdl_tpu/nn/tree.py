"""Tree-structured LSTMs: TreeLSTM base + BinaryTreeLSTM.

Reference: ``DL/nn/TreeLSTM.scala`` and ``DL/nn/BinaryTreeLSTM.scala``
(constituency Tree-LSTM, Tai et al. 2015).  The reference encodes each
tree as a tensor (``TensorTree``, ``BinaryTreeLSTM.scala:478``): row i =
``[left_child, right_child, leaf_index]`` with 1-based indices and 0
meaning "none", and runs a *recursive* Scala forward, dynamically growing
leaf/composer module clones.

TPU redesign: recursion and per-node module clones cannot live under XLA.
Instead a single ``lax.scan`` walks the node array **in topological order
(children before parents — required; 0-padding rows allowed)** carrying
``(c, h)`` buffers for all nodes; each step computes BOTH the leaf and
composer update and selects with ``jnp.where`` (2x compute for
static-shape control flow — the standard TPU trade).  All leaves share
one parameter set and all composers another, which is exactly the
reference's weight-sharing (``shareParams``) without the clone machinery.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.initialization import Xavier
from bigdl_tpu.nn.module import Module


class TreeLSTM(Module):
    """Base: holds sizes (reference ``TreeLSTM.scala``)."""

    def __init__(self, input_size: int, hidden_size: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size


class BinaryTreeLSTM(TreeLSTM):
    """Constituency Tree-LSTM (reference ``BinaryTreeLSTM.scala:40``).

    Input: ``(embeddings (B, n_leaves, input_size),
    trees (B, n_nodes, 3))`` with rows ``[left, right, leaf_idx]``
    (1-based, 0 = none), nodes topologically ordered (children first).
    Output: ``(B, n_nodes, hidden_size)`` — the hidden state of every
    node, matching the reference's output layout.
    """

    def __init__(self, input_size: int, hidden_size: int,
                 gate_output: bool = True, name: Optional[str] = None):
        super().__init__(input_size, hidden_size, name)
        self.gate_output = gate_output

    def init(self, rng):
        ks = jax.random.split(rng, 12)
        D, H = self.input_size, self.hidden_size
        xav = Xavier()

        def lin(k, i, o):
            return {"w": xav.init(k, (i, o), i, o),
                    "b": jnp.zeros((o,))}

        params = {
            # leaf module (reference createLeafModule: c = Wx,
            # o = sigmoid(W_o x), h = o * tanh(c))
            "leaf_c": lin(ks[0], D, H),
            "leaf_o": lin(ks[1], D, H),
            # composer (createComposer): gates from (lh, rh)
            "comp_i_l": lin(ks[2], H, H), "comp_i_r": lin(ks[3], H, H),
            "comp_lf_l": lin(ks[4], H, H), "comp_lf_r": lin(ks[5], H, H),
            "comp_rf_l": lin(ks[6], H, H), "comp_rf_r": lin(ks[7], H, H),
            "comp_u_l": lin(ks[8], H, H), "comp_u_r": lin(ks[9], H, H),
            "comp_o_l": lin(ks[10], H, H), "comp_o_r": lin(ks[11], H, H),
        }
        return params, {}

    @staticmethod
    def _aff(p, x):
        return x @ p["w"] + p["b"]

    def _leaf(self, params, x):
        c = self._aff(params["leaf_c"], x)
        if self.gate_output:
            o = jax.nn.sigmoid(self._aff(params["leaf_o"], x))
            h = o * jnp.tanh(c)
        else:
            h = jnp.tanh(c)
        return c, h

    def _compose(self, params, lc, lh, rc, rh):
        i = jax.nn.sigmoid(self._aff(params["comp_i_l"], lh)
                           + self._aff(params["comp_i_r"], rh))
        lf = jax.nn.sigmoid(self._aff(params["comp_lf_l"], lh)
                            + self._aff(params["comp_lf_r"], rh))
        rf = jax.nn.sigmoid(self._aff(params["comp_rf_l"], lh)
                            + self._aff(params["comp_rf_r"], rh))
        u = jnp.tanh(self._aff(params["comp_u_l"], lh)
                     + self._aff(params["comp_u_r"], rh))
        c = i * u + lf * lc + rf * rc
        if self.gate_output:
            o = jax.nn.sigmoid(self._aff(params["comp_o_l"], lh)
                               + self._aff(params["comp_o_r"], rh))
            h = o * jnp.tanh(c)
        else:
            h = jnp.tanh(c)
        return c, h

    def apply(self, params, state, input, *, training=False, rng=None):
        embeddings, trees = input
        H = self.hidden_size
        n_nodes = trees.shape[1]
        trees = trees.astype(jnp.int32)

        def one_tree(emb, tree):
            # state buffers indexed 1..n_nodes; slot 0 = zeros ("no child")
            c_buf = jnp.zeros((n_nodes + 1, H), emb.dtype)
            h_buf = jnp.zeros((n_nodes + 1, H), emb.dtype)

            def step(carry, node_ix):
                c_buf, h_buf = carry
                left, right, leaf = (tree[node_ix, 0], tree[node_ix, 1],
                                     tree[node_ix, 2])
                is_leaf = (left == 0) & (leaf > 0)
                is_node = left > 0
                x = emb[jnp.maximum(leaf - 1, 0)]
                lc, lh = c_buf[left], h_buf[left]
                rc, rh = c_buf[right], h_buf[right]
                cl, hl = self._leaf(params, x)
                cn, hn = self._compose(params, lc, lh, rc, rh)
                c = jnp.where(is_leaf, cl, jnp.where(is_node, cn, 0.0))
                h = jnp.where(is_leaf, hl, jnp.where(is_node, hn, 0.0))
                c_buf = c_buf.at[node_ix + 1].set(c)
                h_buf = h_buf.at[node_ix + 1].set(h)
                return (c_buf, h_buf), None

            (c_buf, h_buf), _ = lax.scan(step, (c_buf, h_buf),
                                         jnp.arange(n_nodes))
            return h_buf[1:]

        out = jax.vmap(one_tree)(embeddings, trees)
        return out, state
