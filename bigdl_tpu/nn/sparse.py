"""Sparse layers for recommender workloads.

Reference: ``DL/tensor/SparseTensor.scala`` (COO) + ``nn/SparseLinear``,
``nn/LookupTableSparse``, ``nn/SparseJoinTable``, ``nn/DenseToSparse`` —
the Wide&Deep / NCF path named in BASELINE.json.

TPU redesign: COO sparse×dense gemm is the WRONG primitive on TPU (the MXU
wants dense tiles; scatter/gather beats sparse matmul).  The equivalent
representation is **fixed-width id bags**: each sample carries up to
``bag_size`` (id, weight) pairs, padded with id = -1.  A sparse feature
vector x with nnz entries (i, v) then maps to ids=i, weights=v, and
``SparseLinear``'s W @ x becomes a weighted embedding-bag sum — one gather
+ segment-sum, which is exactly how TPU recommenders are built.  Fixed
width keeps shapes static for XLA (ragged bags are bucketed host-side).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.initialization import RandomNormal, RandomUniform


def dense_to_bags(dense: np.ndarray, bag_size: Optional[int] = None):
    """Convert a dense batch (N, D) with few non-zeros into (ids, weights)
    fixed-width bags (host-side helper; reference ``DenseToSparse``)."""
    N, D = dense.shape
    nnz = (dense != 0)
    width = bag_size or int(nnz.sum(axis=1).max())
    ids = np.full((N, width), -1, np.int32)
    weights = np.zeros((N, width), np.float32)
    for n in range(N):
        idx = np.nonzero(nnz[n])[0][:width]
        ids[n, :len(idx)] = idx
        weights[n, :len(idx)] = dense[n, idx]
    return ids, weights


class DenseToSparse(Module):
    """Module form of dense → id-bag conversion (reference
    ``DenseToSparse.scala`` emits a COO SparseTensor; here the sparse
    representation is the fixed-width id bag, see module docstring).

    ``bag_size`` must be static for XLA: the ``bag_size``
    largest-|value| entries are kept (every non-zero when there are
    fewer), the rest padded with id = -1.  Output is the ``(ids,
    weights)`` pair that :class:`SparseLinear` /
    :class:`LookupTableSparse` consume."""

    def __init__(self, bag_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.bag_size = bag_size

    def apply(self, params, state, input, *, training=False, rng=None):
        from jax import lax
        mag, idx = lax.top_k(jnp.abs(input), self.bag_size)
        weights = jnp.take_along_axis(input, idx, axis=-1)
        ids = jnp.where(mag > 0, idx, -1).astype(jnp.int32)
        weights = jnp.where(mag > 0, weights, 0.0)
        return (ids, weights), state


class LookupTableSparse(Module):
    """Embedding bag with combiner (reference ``LookupTableSparse.scala``:
    combiner sum/mean/sqrtn over each sample's ids, optional per-id
    weights).

    Input: ids (N, B) int with -1 padding, or (ids, weights) tuple.
    Output: (N, n_output)."""

    def __init__(self, n_index: int, n_output: int, combiner: str = "sum",
                 weight_init=None, name: Optional[str] = None):
        super().__init__(name)
        assert combiner in ("sum", "mean", "sqrtn")
        self.n_index = n_index
        self.n_output = n_output
        self.combiner = combiner
        self.weight_init = weight_init or RandomNormal(0.0, 0.05)

    def init(self, rng):
        w = self.weight_init.init(rng, (self.n_index, self.n_output),
                                  self.n_index, self.n_output)
        return {"weight": w}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        if isinstance(input, (tuple, list)):
            ids, weights = input
        else:
            ids, weights = input, None
        ids = ids.astype(jnp.int32)
        mask = (ids >= 0)
        safe = jnp.where(mask, ids, 0)
        emb = jnp.take(params["weight"], safe, axis=0)  # (N, B, O)
        w = mask.astype(emb.dtype)
        if weights is not None:
            w = w * weights.astype(emb.dtype)
        summed = jnp.einsum("nbo,nb->no", emb, w)
        if self.combiner == "sum":
            return summed, state
        denom = jnp.sum(jnp.abs(w), axis=1, keepdims=True)
        if self.combiner == "sqrtn":
            denom = jnp.sqrt(jnp.sum(w * w, axis=1, keepdims=True))
        return summed / jnp.maximum(denom, 1e-12), state


class SparseLinear(Module):
    """Affine layer on sparse inputs (reference ``SparseLinear.scala``:
    sparse×dense addmm).  Input: (ids, values) bags representing sparse
    rows of width ``input_size``; computed as a weighted embedding-bag over
    the weight's columns + bias — mathematically identical to W @ x + b."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self._bag = LookupTableSparse(input_size, output_size, "sum",
                                      weight_init=RandomUniform())

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        p, _ = self._bag.init(k1)
        params = {"weight": p["weight"]}  # (input_size, output_size) = W.T
        if self.with_bias:
            params["bias"] = RandomUniform().init(
                k2, (self.output_size,), self.input_size, self.output_size)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        y, _ = self._bag.apply({"weight": params["weight"]}, {}, input)
        if self.with_bias:
            y = y + params["bias"]
        return y, state


class SparseJoinTable(Module):
    """Concatenate bag-form sparse features (reference
    ``SparseJoinTable.scala`` concatenates COO tensors along dim 1).
    Input: sequence of (ids, weights) whose id spaces are offset by each
    predecessor's ``input_size``; sizes given at construction."""

    def __init__(self, sizes, name: Optional[str] = None):
        super().__init__(name)
        self.sizes = list(sizes)

    def apply(self, params, state, input, *, training=False, rng=None):
        ids_out, w_out = [], []
        offset = 0
        for (ids, w), size in zip(input, self.sizes):
            mask = ids >= 0
            ids_out.append(jnp.where(mask, ids + offset, -1))
            w_out.append(w)
            offset += size
        return (jnp.concatenate(ids_out, axis=1),
                jnp.concatenate(w_out, axis=1)), state
