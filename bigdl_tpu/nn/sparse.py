"""Sparse layers for recommender workloads.

Reference: ``DL/tensor/SparseTensor.scala`` (COO) + ``nn/SparseLinear``,
``nn/LookupTableSparse``, ``nn/SparseJoinTable``, ``nn/DenseToSparse`` —
the Wide&Deep / NCF path named in BASELINE.json.

TPU redesign, two sparse representations:

1. **Fixed-width id bags** (ids (N, B) with -1 padding + weights): COO
   sparse×dense gemm is the WRONG primitive on TPU (the MXU wants dense
   tiles; scatter/gather beats sparse matmul), so a sparse feature
   vector maps to a weighted embedding-bag sum — one gather +
   batched reduction.  Best when every sample has a similar, small nnz.

2. **Batch COO** (:class:`COOBatch`: flat ``row``/``col``/``values``
   with a static total-nnz, the device form of the reference's
   ``SparseMiniBatch``, ``DL/dataset/MiniBatch.scala:588`` /
   ``SparseTensorBLAS.scala``): the whole batch's non-zeros in one flat
   stream, executed with ``jax.ops.segment_sum`` kernels.  Best for
   ragged nnz (no per-sample width cap); host batching pads the flat
   stream to an nnz bucket so shapes stay static for XLA
   (``dataset/sample.py`` ``batch_sparse_samples``).

Both forms feed the same layers: :class:`SparseLinear` /
:class:`LookupTableSparse` accept bags or a :class:`COOBatch`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.initialization import RandomNormal, RandomUniform


@dataclass(frozen=True)
class COOBatch:
    """Device-side batch-COO sparse matrix of shape ``dense_shape`` =
    (N, D): ``values[k]`` sits at (``row[k]``, ``col[k]``).  Padding
    entries carry ``row = col = 0, value = 0`` (they contribute
    nothing).  ``dense_shape`` is static (pytree metadata) so
    ``segment_sum`` gets a compile-time segment count."""

    row: jnp.ndarray      # (NNZ,) int32
    col: jnp.ndarray      # (NNZ,) int32
    values: jnp.ndarray   # (NNZ,) float
    dense_shape: Tuple[int, int]

    @property
    def n_rows(self) -> int:
        return self.dense_shape[0]

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.row, self.col].add(self.values)


jax.tree_util.register_dataclass(
    COOBatch, data_fields=["row", "col", "values"],
    meta_fields=["dense_shape"])


def coo_spmm(coo: COOBatch, dense, impl: Optional[str] = None):
    """Sparse×dense matmul ``(N, D) @ (D, O) -> (N, O)`` as gather +
    segment-sum (the reference's ``SparseTensorBLAS`` coomm role, built
    on the TPU-friendly primitive instead of a sparse gemm).

    ``impl``: custom-kernel selection (``None`` defers to
    ``Engine.kernel_impl()``).  With ``"pallas"`` and a shape the
    measured ``pallas_embed.supported`` gate accepts, the whole
    gather + scale + segment-accumulate runs as ONE fused kernel with
    no materialized ``(nnz, O)`` intermediate — the Wide&Deep hot path
    (``ops/pallas_embed.py``); anything else takes this XLA chain."""
    from bigdl_tpu.ops import pallas_embed, resolve_kernel_impl
    # static gate: impl resolution is host config, n_rows/dense_shape
    # are pytree metadata and shapes/dtypes are trace-time constants
    if resolve_kernel_impl(impl) == "pallas" and pallas_embed.supported(
            coo.row.shape[0], coo.n_rows, dense.shape, dense.dtype):
        return pallas_embed.embedding_bag_coo(
            coo.row, coo.col, coo.values, dense, coo.n_rows)
    gathered = jnp.take(dense, coo.col, axis=0) * coo.values[:, None]
    return jax.ops.segment_sum(gathered, coo.row,
                               num_segments=coo.n_rows)


def coo_row_reduce(coo: COOBatch, values):
    """Per-row sum of ``values`` (one scalar per non-zero)."""
    return jax.ops.segment_sum(values, coo.row, num_segments=coo.n_rows)


def dense_to_bags(dense: np.ndarray, bag_size: Optional[int] = None):
    """Convert a dense batch (N, D) with few non-zeros into (ids, weights)
    fixed-width bags (host-side helper; reference ``DenseToSparse``)."""
    N, D = dense.shape
    nnz = (dense != 0)
    width = bag_size or int(nnz.sum(axis=1).max())
    ids = np.full((N, width), -1, np.int32)
    weights = np.zeros((N, width), np.float32)
    for n in range(N):
        idx = np.nonzero(nnz[n])[0][:width]
        ids[n, :len(idx)] = idx
        weights[n, :len(idx)] = dense[n, idx]
    return ids, weights


class DenseToSparse(Module):
    """Module form of dense → id-bag conversion (reference
    ``DenseToSparse.scala`` emits a COO SparseTensor; here the sparse
    representation is the fixed-width id bag, see module docstring).

    ``bag_size`` must be static for XLA: the ``bag_size``
    largest-|value| entries are kept (every non-zero when there are
    fewer), the rest padded with id = -1.  Output is the ``(ids,
    weights)`` pair that :class:`SparseLinear` /
    :class:`LookupTableSparse` consume."""

    def __init__(self, bag_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.bag_size = bag_size

    def apply(self, params, state, input, *, training=False, rng=None):
        from jax import lax
        mag, idx = lax.top_k(jnp.abs(input), self.bag_size)
        weights = jnp.take_along_axis(input, idx, axis=-1)
        ids = jnp.where(mag > 0, idx, -1).astype(jnp.int32)
        weights = jnp.where(mag > 0, weights, 0.0)
        return (ids, weights), state


class LookupTableSparse(Module):
    """Embedding bag with combiner (reference ``LookupTableSparse.scala``:
    combiner sum/mean/sqrtn over each sample's ids, optional per-id
    weights).

    Input: ids (N, B) int with -1 padding, a (ids, weights) tuple, or a
    :class:`COOBatch` (rows = samples, cols = ids, values = weights).
    Output: (N, n_output)."""

    def __init__(self, n_index: int, n_output: int, combiner: str = "sum",
                 weight_init=None, name: Optional[str] = None,
                 impl: Optional[str] = None):
        super().__init__(name)
        assert combiner in ("sum", "mean", "sqrtn")
        self.n_index = n_index
        self.n_output = n_output
        self.combiner = combiner
        self.weight_init = weight_init or RandomNormal(0.0, 0.05)
        # COO-path kernel choice (see coo_spmm); None = Engine default
        self.impl = impl

    def init(self, rng):
        w = self.weight_init.init(rng, (self.n_index, self.n_output),
                                  self.n_index, self.n_output)
        return {"weight": w}, {}

    def _apply_coo(self, params, coo: COOBatch):
        summed = coo_spmm(coo, params["weight"], impl=self.impl)
        if self.combiner == "sum":
            return summed
        w = coo.values
        if self.combiner == "mean":
            # reference LookupTableSparse.scala:123-133 accumulates RAW
            # weights (batchScale = 1/sum(w)), so negative per-id weights
            # must flow through un-absed; guard only exact zeros
            denom = coo_row_reduce(coo, w)
            denom = jnp.where(jnp.abs(denom) < 1e-12, 1e-12, denom)
        else:  # sqrtn
            denom = jnp.maximum(jnp.sqrt(coo_row_reduce(coo, w * w)), 1e-12)
        return summed / denom[:, None]

    def apply(self, params, state, input, *, training=False, rng=None):
        if isinstance(input, COOBatch):
            return self._apply_coo(params, input), state
        if isinstance(input, (tuple, list)):
            ids, weights = input
        else:
            ids, weights = input, None
        ids = ids.astype(jnp.int32)
        mask = (ids >= 0)
        safe = jnp.where(mask, ids, 0)
        emb = jnp.take(params["weight"], safe, axis=0)  # (N, B, O)
        w = mask.astype(emb.dtype)
        if weights is not None:
            w = w * weights.astype(emb.dtype)
        summed = jnp.einsum("nbo,nb->no", emb, w)
        if self.combiner == "sum":
            return summed, state
        if self.combiner == "sqrtn":
            denom = jnp.maximum(
                jnp.sqrt(jnp.sum(w * w, axis=1, keepdims=True)), 1e-12)
        else:  # mean: raw weight sum (reference LookupTableSparse.scala:123)
            denom = jnp.sum(w, axis=1, keepdims=True)
            denom = jnp.where(jnp.abs(denom) < 1e-12, 1e-12, denom)
        return summed / denom, state


class SparseLinear(Module):
    """Affine layer on sparse inputs (reference ``SparseLinear.scala``:
    sparse×dense addmm).  Input: (ids, values) bags representing sparse
    rows of width ``input_size``, or a :class:`COOBatch`; computed as a
    weighted embedding-bag / segment-sum over the weight's columns +
    bias — mathematically identical to W @ x + b."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True, name: Optional[str] = None,
                 impl: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        # COO-path kernel choice (see coo_spmm); None = Engine default
        self.impl = impl
        self._bag = LookupTableSparse(input_size, output_size, "sum",
                                      weight_init=RandomUniform(),
                                      impl=impl)

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        p, _ = self._bag.init(k1)
        params = {"weight": p["weight"]}  # (input_size, output_size) = W.T
        if self.with_bias:
            params["bias"] = RandomUniform().init(
                k2, (self.output_size,), self.input_size, self.output_size)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        if isinstance(input, COOBatch):
            y = coo_spmm(input, params["weight"], impl=self.impl)
        else:
            y, _ = self._bag.apply({"weight": params["weight"]}, {}, input)
        if self.with_bias:
            y = y + params["bias"]
        return y, state


class SparseJoinTable(Module):
    """Concatenate sparse features along dim 1 (reference
    ``SparseJoinTable.scala`` concatenates COO tensors).
    Input: sequence of (ids, weights) bags OR of :class:`COOBatch`es,
    whose id spaces are offset by each predecessor's ``input_size``;
    sizes given at construction."""

    def __init__(self, sizes, name: Optional[str] = None):
        super().__init__(name)
        self.sizes = list(sizes)

    def apply(self, params, state, input, *, training=False, rng=None):
        if all(isinstance(t, COOBatch) for t in input):
            rows, cols, vals = [], [], []
            offset = 0
            n = input[0].n_rows
            # n_rows is static pytree metadata (dense_shape), not a tracer
            if any(coo.n_rows != n for coo in input):
                raise ValueError(
                    "SparseJoinTable inputs disagree on batch size: "
                    f"{[coo.n_rows for coo in input]}")
            for coo, size in zip(input, self.sizes):
                rows.append(coo.row)
                cols.append(coo.col + offset)
                vals.append(coo.values)
                offset += size
            return COOBatch(jnp.concatenate(rows), jnp.concatenate(cols),
                            jnp.concatenate(vals), (n, offset)), state
        ids_out, w_out = [], []
        offset = 0
        for (ids, w), size in zip(input, self.sizes):
            mask = ids >= 0
            ids_out.append(jnp.where(mask, ids + offset, -1))
            w_out.append(w)
            offset += size
        return (jnp.concatenate(ids_out, axis=1),
                jnp.concatenate(w_out, axis=1)), state
