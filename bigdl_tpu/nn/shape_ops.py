"""Tensor-manipulation and table (pytree) layers.

Reference: the large family of shape/table layers at ``DL/nn/`` —
``Reshape``, ``View``, ``Squeeze``, ``Transpose``, ``Narrow``, ``Select``,
``JoinTable``, ``SplitTable``, ``CAddTable``, ``CMulTable``, ``MulConstant``,
``Power``, ``Mean``, ``Sum`` … Each is a thin jnp expression; they exist so
BigDL-style ``Sequential`` graphs translate one-to-one.

Dims here are 0-based with batch at axis 0.  The reference is Torch-style
1-based; its common idiom "dim 1 = feature" maps to ``dim=1`` here too
because batch occupies axis 0 in both conventions when batched.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class Reshape(Module):
    """Reshape keeping the batch axis (reference ``Reshape.scala`` with
    batchMode=Some(true) semantics)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = True, name=None):
        super().__init__(name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, state, input, *, training=False, rng=None):
        if self.batch_mode:
            return input.reshape((input.shape[0],) + self.size), state
        return input.reshape(self.size), state


class View(Reshape):
    """Alias of Reshape (reference ``View.scala``; -1 inference supported
    by jnp.reshape)."""
    pass


class Flatten(Module):
    """Flatten all non-batch dims (BigDL scripts use Reshape for this; kept
    as sugar)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input.reshape(input.shape[0], -1), state


class Squeeze(Module):
    def __init__(self, dim: Optional[int] = None, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.squeeze(input, axis=self.dim), state


class Unsqueeze(Module):
    def __init__(self, pos: int, name=None):
        super().__init__(name)
        self.pos = pos

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.expand_dims(input, self.pos), state


class Transpose(Module):
    """Swap listed dim pairs (reference ``Transpose.scala``)."""

    def __init__(self, permutations: Sequence[tuple[int, int]], name=None):
        super().__init__(name)
        self.permutations = list(permutations)

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input
        for a, b in self.permutations:
            out = jnp.swapaxes(out, a, b)
        return out, state


class Contiguous(Module):
    """No-op under XLA (reference ``Contiguous.scala`` forces a copy for
    MKL; XLA owns layout)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state


class Narrow(Module):
    """Slice ``length`` elements from ``offset`` along ``dim``
    (reference ``Narrow.scala``; offset 0-based here)."""

    def __init__(self, dim: int, offset: int, length: int, name=None):
        super().__init__(name)
        self.dim, self.offset, self.length = dim, offset, length

    def apply(self, params, state, input, *, training=False, rng=None):
        n = self.length if self.length >= 0 \
            else input.shape[self.dim] - self.offset + self.length + 1
        return jax.lax.slice_in_dim(input, self.offset, self.offset + n,
                                    axis=self.dim), state


class Select(Module):
    """Select index along dim, dropping it (reference ``Select.scala``)."""

    def __init__(self, dim: int, index: int, name=None):
        super().__init__(name)
        self.dim, self.index = dim, index

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.take(input, self.index, axis=self.dim), state


class Index(Module):
    """Gather rows along dim by an index tensor: input=(tensor, indices)
    (reference ``Index.scala``)."""

    def __init__(self, dim: int, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        x, idx = input
        return jnp.take(x, idx.astype(jnp.int32), axis=self.dim), state


class Padding(Module):
    """Pad ``pad`` zeros (or ``value``) on one side of ``dim``
    (reference ``Padding.scala``: negative pad → leading side)."""

    def __init__(self, dim: int, pad: int, value: float = 0.0, name=None):
        super().__init__(name)
        self.dim, self.pad, self.value = dim, pad, value

    def apply(self, params, state, input, *, training=False, rng=None):
        cfg = [(0, 0)] * input.ndim
        cfg[self.dim] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(input, cfg, constant_values=self.value), state


class SpatialZeroPadding(Module):
    """(reference ``SpatialZeroPadding.scala``) pad H/W of NCHW."""

    def __init__(self, pad_left: int, pad_right: int, pad_top: int,
                 pad_bottom: int, name=None):
        super().__init__(name)
        self.cfg = (pad_left, pad_right, pad_top, pad_bottom)

    def apply(self, params, state, input, *, training=False, rng=None):
        l, r, t, b = self.cfg
        return jnp.pad(input, ((0, 0), (0, 0), (t, b), (l, r))), state


class JoinTable(Module):
    """Concatenate a table of tensors along dim (reference
    ``JoinTable.scala``)."""

    def __init__(self, dimension: int, n_input_dims: int = -1, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.concatenate(list(input), axis=self.dimension), state


class SplitTable(Module):
    """Split a tensor into a table along dim (reference
    ``SplitTable.scala``)."""

    def __init__(self, dimension: int, name=None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        n = input.shape[self.dimension]
        parts = jnp.split(input, n, axis=self.dimension)
        return tuple(jnp.squeeze(p, axis=self.dimension) for p in parts), state


class CAddTable(Module):
    """Elementwise sum of a table (reference ``CAddTable.scala`` — the
    ResNet shortcut join)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = out + x
        return out, state


class CMulTable(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = out * x
        return out, state


class CSubTable(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return input[0] - input[1], state


class CDivTable(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return input[0] / input[1], state


class CMaxTable(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = jnp.maximum(out, x)
        return out, state


class CMinTable(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = jnp.minimum(out, x)
        return out, state


class FlattenTable(Module):
    """Flatten nested table (reference ``FlattenTable.scala``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        flat = []

        def rec(t):
            if isinstance(t, (tuple, list)):
                for e in t:
                    rec(e)
            else:
                flat.append(t)

        rec(input)
        return tuple(flat), state


class SelectTable(Module):
    def __init__(self, index: int, name=None):
        super().__init__(name)
        self.index = index

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[self.index], state


class MulConstant(Module):
    def __init__(self, scalar: float, name=None):
        super().__init__(name)
        self.scalar = scalar

    def apply(self, params, state, input, *, training=False, rng=None):
        return input * self.scalar, state


class AddConstant(Module):
    def __init__(self, constant_scalar: float, name=None):
        super().__init__(name)
        self.constant_scalar = constant_scalar

    def apply(self, params, state, input, *, training=False, rng=None):
        return input + self.constant_scalar, state


class Power(Module):
    """(shift + scale*x)^power (reference ``Power.scala``)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 name=None):
        super().__init__(name)
        self.power, self.scale, self.shift = power, scale, shift

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.power(self.shift + self.scale * input, self.power), state


class Sqrt(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.sqrt(input), state


class Square(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return input * input, state


class Abs(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.abs(input), state


class Exp(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.exp(input), state


class Log(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.log(input), state


class Clamp(Module):
    def __init__(self, min_v: float, max_v: float, name=None):
        super().__init__(name)
        self.min_v, self.max_v = min_v, max_v

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.clip(input, self.min_v, self.max_v), state


class Mean(Module):
    """(reference ``Mean.scala``) mean over ``dimension``; squeeze like the
    reference (squeeze=true default)."""

    def __init__(self, dimension: int = 0, squeeze: bool = True, name=None):
        super().__init__(name)
        self.dimension, self.squeeze = dimension, squeeze

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.mean(input, axis=self.dimension,
                        keepdims=not self.squeeze), state


class Sum(Module):
    def __init__(self, dimension: int = 0, squeeze: bool = True, name=None):
        super().__init__(name)
        self.dimension, self.squeeze = dimension, squeeze

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.sum(input, axis=self.dimension,
                       keepdims=not self.squeeze), state


class Max(Module):
    def __init__(self, dim: int = 0, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.max(input, axis=self.dim), state


class Min(Module):
    def __init__(self, dim: int = 0, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.min(input, axis=self.dim), state


class Replicate(Module):
    """Insert a new dim of size n_features (reference ``Replicate.scala``)."""

    def __init__(self, n_features: int, dim: int = 0, name=None):
        super().__init__(name)
        self.n_features, self.dim = n_features, dim

    def apply(self, params, state, input, *, training=False, rng=None):
        out = jnp.expand_dims(input, self.dim)
        reps = [1] * out.ndim
        reps[self.dim] = self.n_features
        return jnp.tile(out, reps), state


class Pack(Module):
    """Stack a table along a new dim (reference ``Pack.scala``)."""

    def __init__(self, dim: int, name=None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.stack(list(input), axis=self.dim), state


class Scale(Module):
    """CMul + CAdd (reference ``Scale.scala``)."""

    def __init__(self, size: Sequence[int], name=None):
        super().__init__(name)
        from bigdl_tpu.nn.layers import CMul, CAdd
        self.cmul = CMul(size)
        self.cadd = CAdd(size)

    def spec_children(self):
        return {"mul": self.cmul, "add": self.cadd}

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        p1, _ = self.cmul.init(k1)
        p2, _ = self.cadd.init(k2)
        return {"mul": p1, "add": p2}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        y, _ = self.cmul.apply(params["mul"], {}, input)
        y, _ = self.cadd.apply(params["add"], {}, y)
        return y, state


class Masking(Module):
    """Zero timesteps equal to mask_value (reference ``Masking.scala``)."""

    def __init__(self, mask_value: float = 0.0, name=None):
        super().__init__(name)
        self.mask_value = mask_value

    def apply(self, params, state, input, *, training=False, rng=None):
        keep = jnp.any(input != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, input, 0.0), state
