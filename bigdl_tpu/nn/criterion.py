"""Criterions (loss functions).

Reference: ``DL/nn/AbstractCriterion`` + the ~40 criterion files
(``ClassNLLCriterion``, ``MSECriterion``, ``BCECriterion``,
``SmoothL1Criterion``, ``DistKLDivCriterion``, ``MarginCriterion``, …).

Functional contract: ``apply(input, target) -> scalar`` (pure; jit/grad
compatible).  The reference's hand-written ``updateGradInput`` is replaced
by ``jax.grad`` of the loss.  Class targets are 0-based integer arrays
(reference/Torch is 1-based).

``size_average=True`` (the reference default) averages over the batch.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class Criterion:
    """Base class.  Eager convenience mirrors AbstractCriterion:
    ``forward(input, target)`` returns the loss; ``backward`` returns
    d loss/d input via jax.grad."""

    size_average: bool = True

    def apply(self, input, target):
        raise NotImplementedError

    def forward(self, input, target):
        self.output = self.apply(input, target)
        return self.output

    def __call__(self, input, target):
        return self.forward(input, target)

    def backward(self, input, target):
        self.grad_input = jax.grad(lambda x: self.apply(x, target))(input)
        return self.grad_input

    def _reduce(self, losses):
        """Batch reduction policy: mean when ``size_average`` (the reference
        default), else sum."""
        return jnp.mean(losses) if self.size_average else jnp.sum(losses)


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probabilities (pair with LogSoftMax;
    reference ``ClassNLLCriterion.scala``).  Supports class weights and
    padding via ``ignore_index`` (maps the reference's logProbAsInput /
    paddingValue behaviors)."""

    def __init__(self, weights: Optional[jnp.ndarray] = None,
                 size_average: bool = True, logits: bool = False,
                 ignore_index: int = -100):
        self.weights = weights
        self.size_average = size_average
        self.logits = logits  # if True, input is raw logits, not log-probs
        self.ignore_index = ignore_index

    def apply(self, input, target):
        logp = jax.nn.log_softmax(input, axis=-1) if self.logits else input
        t = target.astype(jnp.int32)
        valid = (t != self.ignore_index)
        t_safe = jnp.where(valid, t, 0)
        picked = jnp.take_along_axis(logp, t_safe[..., None], axis=-1)[..., 0]
        w = jnp.ones_like(picked)
        if self.weights is not None:
            w = jnp.take(self.weights, t_safe)
        w = jnp.where(valid, w, 0.0)
        total = -jnp.sum(w * picked)
        if self.size_average:
            return total / jnp.maximum(jnp.sum(w), 1e-8)
        return total


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (reference ``CrossEntropyCriterion.scala``)."""

    def __init__(self, weights: Optional[jnp.ndarray] = None,
                 size_average: bool = True):
        self._nll = ClassNLLCriterion(weights, size_average, logits=True)
        self.size_average = size_average

    def apply(self, input, target):
        return self._nll.apply(input, target)


class MSECriterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def apply(self, input, target):
        d = (input - target) ** 2
        return self._reduce(d)


class AbsCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def apply(self, input, target):
        d = jnp.abs(input - target)
        return self._reduce(d)


class BCECriterion(Criterion):
    """Binary cross entropy on probabilities (reference
    ``BCECriterion.scala``; clamps like the reference's eps)."""

    def __init__(self, weights: Optional[jnp.ndarray] = None,
                 size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def apply(self, input, target):
        # eps must be representable at the input dtype: 1 - 1e-12 == 1.0 in
        # f32, which would let a saturated sigmoid produce log(0) = -inf
        eps = jnp.finfo(jnp.result_type(input.dtype, jnp.float32)).eps
        x = jnp.clip(input.astype(jnp.float32), eps, 1.0 - eps)
        l = -(target * jnp.log(x) + (1.0 - target) * jnp.log1p(-x))
        if self.weights is not None:
            l = l * self.weights
        return self._reduce(l)


class BCEWithLogitsCriterion(Criterion):
    """Numerically-stable BCE on logits (not separate in the reference;
    included because it is the stable form on TPU bf16)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def apply(self, input, target):
        l = jnp.maximum(input, 0) - input * target + jnp.log1p(
            jnp.exp(-jnp.abs(input)))
        return self._reduce(l)


class SmoothL1Criterion(Criterion):
    """Huber loss with delta 1 (reference ``SmoothL1Criterion.scala``)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def apply(self, input, target):
        d = jnp.abs(input - target)
        l = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return self._reduce(l)


class DistKLDivCriterion(Criterion):
    """KL(target || input) with input = log-probs (reference
    ``DistKLDivCriterion.scala``)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def apply(self, input, target):
        l = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12))
                                            - input), 0.0)
        # reference averages over batch dim (sizeAverage), else sums all
        if self.size_average:
            return jnp.sum(l) / input.shape[0]
        return jnp.sum(l)


class KLDCriterion(Criterion):
    """VAE latent KL: input=(mean, log_var), target unused
    (reference ``KLDCriterion.scala``)."""

    def apply(self, input, target=None):
        mean, log_var = input
        kl = 0.5 * jnp.sum(mean ** 2 + jnp.exp(log_var) - 1.0 - log_var,
                           axis=-1)
        return jnp.mean(kl)


class GaussianCriterion(Criterion):
    """Negative log-likelihood of a diagonal Gaussian: input=(mean,log_var)
    (reference ``GaussianCriterion.scala``)."""

    def apply(self, input, target):
        mean, log_var = input
        nll = 0.5 * (jnp.log(2 * jnp.pi) + log_var
                     + (target - mean) ** 2 / jnp.exp(log_var))
        return jnp.sum(nll) / target.shape[0]


class MarginCriterion(Criterion):
    """Hinge loss; target in {-1, 1} (reference ``MarginCriterion.scala``;
    squared=False default)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True,
                 squared: bool = False):
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def apply(self, input, target):
        l = jnp.maximum(0.0, self.margin - input * target)
        if self.squared:
            l = l * l
        return self._reduce(l)


class MarginRankingCriterion(Criterion):
    """input=(x1, x2); target ±1 (reference ``MarginRankingCriterion.scala``)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        x1, x2 = input
        l = jnp.maximum(0.0, -target * (x1 - x2) + self.margin)
        return self._reduce(l)


class CosineEmbeddingCriterion(Criterion):
    """input=(x1, x2); target 1 → pull together, -1 → push apart
    (reference ``CosineEmbeddingCriterion.scala``)."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        x1, x2 = input
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        l = jnp.where(target > 0, 1.0 - cos,
                      jnp.maximum(0.0, cos - self.margin))
        return self._reduce(l)


class HingeEmbeddingCriterion(Criterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        l = jnp.where(target > 0, input,
                      jnp.maximum(0.0, self.margin - input))
        return self._reduce(l)


class SoftMarginCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def apply(self, input, target):
        l = jnp.log1p(jnp.exp(-input * target))
        return self._reduce(l)


class L1Cost(Criterion):
    """(reference ``L1Cost.scala``) sum |x|; target ignored."""

    def apply(self, input, target=None):
        return jnp.sum(jnp.abs(input))


class DiceCoefficientCriterion(Criterion):
    """1 - dice overlap (reference ``DiceCoefficientCriterion.scala``)."""

    def __init__(self, epsilon: float = 1.0):
        self.epsilon = epsilon

    def apply(self, input, target):
        axes = tuple(range(1, input.ndim))
        num = 2.0 * jnp.sum(input * target, axes) + self.epsilon
        den = jnp.sum(input, axes) + jnp.sum(target, axes) + self.epsilon
        return jnp.mean(1.0 - num / den)


class MultiLabelSoftMarginCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def apply(self, input, target):
        l = -(target * jax.nn.log_sigmoid(input)
              + (1 - target) * jax.nn.log_sigmoid(-input))
        return self._reduce(l)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (reference ``MultiCriterion.scala``)."""

    def __init__(self):
        self.criterions: list[tuple[Criterion, float]] = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append((criterion, weight))
        return self

    def apply(self, input, target):
        return sum(w * c.apply(input, target) for c, w in self.criterions)


class ParallelCriterion(Criterion):
    """i-th criterion on (input[i], target[i]) (reference
    ``ParallelCriterion.scala``)."""

    def __init__(self, repeat_target: bool = False):
        self.criterions: list[tuple[Criterion, float]] = []
        self.repeat_target = repeat_target

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append((criterion, weight))
        return self

    def apply(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(self.criterions):
            t = target if self.repeat_target else target[i]
            total = total + w * c.apply(input[i], t)
        return total


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (N, T, ...) input
    (reference ``TimeDistributedCriterion.scala``)."""

    def __init__(self, critrn: Criterion, size_average: bool = False):
        self.critrn = critrn
        self.size_average = size_average

    def apply(self, input, target):
        """Reference semantics: per-step loss is summed over timesteps, then
        divided by T iff ``size_average``.  The inner criterion reduces over
        the batch; flattening (N,T,...) → (N*T,...) means a mean-reducing
        inner criterion yields sum_t(loss_t)/T already, and a sum-reducing
        one yields sum_t(loss_t)."""
        T = input.shape[1]
        x = input.reshape((-1,) + input.shape[2:])
        t = target.reshape((-1,) + target.shape[2:])
        loss = self.critrn.apply(x, t)
        inner_mean = getattr(self.critrn, "size_average", True)
        if inner_mean:
            return loss if self.size_average else loss * T
        return loss / T if self.size_average else loss


class PGCriterion(Criterion):
    """Policy-gradient criterion: -sum(log(p) * reward)
    (reference ``PGCriterion.scala``)."""

    def __init__(self, size_average: bool = False):
        self.size_average = size_average

    def apply(self, input, target):
        l = -jnp.log(jnp.maximum(input, 1e-12)) * target
        return self._reduce(l)


class MultiLabelMarginCriterion(Criterion):
    """Multi-class multi-label hinge (reference
    ``MultiLabelMarginCriterion.scala``).  Targets: per-row 0-based class
    indices padded with -1."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def apply(self, input, target):
        t = target.astype(jnp.int32)
        valid = (t >= 0)
        t_safe = jnp.where(valid, t, 0)
        tgt_scores = jnp.take_along_axis(input, t_safe, axis=-1)
        # for each (sample, class j not in targets, target k): max(0, 1 - (x[k]-x[j]))
        # scatter-add then >0 so a padding slot (t_safe=0, valid=False) can't
        # clobber a genuine class-0 target at the same index
        hits = jnp.zeros_like(input, dtype=jnp.int32)
        hits = jax.vmap(lambda m, idx, v: m.at[idx].add(v))(
            hits, t_safe, valid.astype(jnp.int32))
        is_target = hits > 0
        margins = 1.0 - (tgt_scores[:, :, None] - input[:, None, :])
        margins = jnp.where(valid[:, :, None] & ~is_target[:, None, :],
                            jnp.maximum(margins, 0.0), 0.0)
        l = jnp.sum(margins, axis=(1, 2)) / input.shape[-1]
        return self._reduce(l)


class SoftmaxWithCriterion(Criterion):
    """Caffe-style softmax loss on NCHW maps (reference
    ``SoftmaxWithCriterion.scala``)."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "VALID"):
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def apply(self, input, target):
        # input (N, C, H, W), target (N, H, W) int
        logp = jax.nn.log_softmax(input, axis=1)
        t = target.astype(jnp.int32)
        valid = jnp.ones_like(t, dtype=bool) if self.ignore_label is None \
            else (t != self.ignore_label)
        t_safe = jnp.where(valid, t, 0)
        picked = jnp.take_along_axis(logp, t_safe[:, None], axis=1)[:, 0]
        total = -jnp.sum(jnp.where(valid, picked, 0.0))
        if self.normalize_mode == "VALID":
            return total / jnp.maximum(jnp.sum(valid), 1)
        elif self.normalize_mode == "BATCH_SIZE":
            return total / input.shape[0]
        return total


# --------------------------------------------------------------------------
# round-2 criterion breadth (VERDICT missing item: ~16 criterions)
# --------------------------------------------------------------------------


class CosineDistanceCriterion(Criterion):
    """``1 - cos(input, target)`` per sample (reference
    ``CosineDistanceCriterion.scala``)."""

    def __init__(self, size_average: bool = True, eps: float = 1e-12):
        self.size_average = size_average
        self.eps = eps

    def apply(self, input, target):
        x = input.reshape(input.shape[0], -1)
        y = target.reshape(target.shape[0], -1)
        num = jnp.sum(x * y, axis=-1)
        den = jnp.linalg.norm(x, axis=-1) * jnp.linalg.norm(y, axis=-1)
        return self._reduce(1.0 - num / jnp.maximum(den, self.eps))


class CosineProximityCriterion(Criterion):
    """Keras ``cosine_proximity``: negative cosine similarity of
    l2-normalized input/target (reference ``CosineProximityCriterion.scala``)."""

    def __init__(self, eps: float = 1e-12):
        self.eps = eps

    def apply(self, input, target):
        x = input.reshape(input.shape[0], -1)
        y = target.reshape(target.shape[0], -1)
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True),
                             self.eps)
        yn = y / jnp.maximum(jnp.linalg.norm(y, axis=-1, keepdims=True),
                             self.eps)
        return -jnp.mean(jnp.sum(xn * yn, axis=-1))


class DotProductCriterion(Criterion):
    """Dot product of input and target (reference
    ``DotProductCriterion.scala`` — used as the surrogate loss whose
    gradient w.r.t. input is the target, e.g. for policy gradients)."""

    def __init__(self, size_average: bool = False):
        self.size_average = size_average

    def apply(self, input, target):
        dot = jnp.sum(input * target)
        if self.size_average and input.ndim == 2:
            return dot / input.shape[0]
        return dot


class KullbackLeiblerDivergenceCriterion(Criterion):
    """Keras ``kld`` on probability inputs with clipping (reference
    ``KullbackLeiblerDivergenceCriterion.scala``; distinct from
    DistKLDivCriterion which takes log-probs)."""

    def __init__(self, eps: float = 1e-7):
        self.eps = eps

    def apply(self, input, target):
        y = jnp.clip(target, self.eps, 1.0)
        p = jnp.clip(input, self.eps, 1.0)
        per = jnp.sum((y * jnp.log(y / p)).reshape(input.shape[0], -1),
                      axis=-1)
        return jnp.mean(per)


class L1HingeEmbeddingCriterion(Criterion):
    """Pair input ``(x1, x2)``, label y ∈ {1, -1}: L1 distance if similar,
    hinge on the margin if dissimilar (reference
    ``L1HingeEmbeddingCriterion.scala``)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        x1, x2 = input
        d = jnp.sum(jnp.abs(x1 - x2).reshape(x1.shape[0], -1), axis=-1)
        y = target.reshape(-1)
        l = jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))
        return self._reduce(l)


class MeanAbsolutePercentageCriterion(Criterion):
    """Keras ``mape`` (reference ``MeanAbsolutePercentageCriterion.scala``)."""

    def __init__(self, eps: float = 1e-7):
        self.eps = eps

    def apply(self, input, target):
        diff = jnp.abs(target - input) / jnp.clip(jnp.abs(target),
                                                  self.eps, None)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(Criterion):
    """Keras ``msle`` (reference ``MeanSquaredLogarithmicCriterion.scala``)."""

    def __init__(self, eps: float = 1e-7):
        self.eps = eps

    def apply(self, input, target):
        a = jnp.log(jnp.clip(input, self.eps, None) + 1.0)
        b = jnp.log(jnp.clip(target, self.eps, None) + 1.0)
        return jnp.mean((a - b) ** 2)


class MultiMarginCriterion(Criterion):
    """Multi-class margin loss (reference ``MultiMarginCriterion.scala``):
    ``mean_i sum_{j != y_i} max(0, margin - x[y_i] + x[j])^p / dim``."""

    def __init__(self, p: int = 1, weights: Optional[jnp.ndarray] = None,
                 margin: float = 1.0, size_average: bool = True):
        if p not in (1, 2):
            raise ValueError("MultiMarginCriterion supports p=1 or 2")
        self.p = p
        self.weights = weights
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        t = target.astype(jnp.int32).reshape(-1)
        x_y = jnp.take_along_axis(input, t[:, None], axis=-1)
        m = jnp.maximum(0.0, self.margin - x_y + input)
        if self.p == 2:
            m = m * m
        if self.weights is not None:
            m = m * jnp.take(self.weights, t)[:, None]
        # zero the target class's own column
        m = m * (1.0 - jax.nn.one_hot(t, input.shape[-1], dtype=input.dtype))
        l = jnp.sum(m, axis=-1) / input.shape[-1]
        return self._reduce(l)


class PoissonCriterion(Criterion):
    """Keras ``poisson``: ``mean(pred - target * log(pred))`` (reference
    ``PoissonCriterion.scala``)."""

    def __init__(self, eps: float = 1e-7):
        self.eps = eps

    def apply(self, input, target):
        return jnp.mean(input - target * jnp.log(jnp.clip(input, self.eps,
                                                          None)))


class ClassSimplexCriterion(Criterion):
    """MSE against a regular-simplex embedding of each class (reference
    ``ClassSimplexCriterion.scala``: nClasses points on an
    (nClasses-1)-simplex, scaled so targets have unit-ish norm)."""

    def __init__(self, n_classes: int):
        if n_classes < 2:
            raise ValueError("n_classes must be > 1")
        self.n_classes = n_classes
        self.simplex = jnp.asarray(self._regsplex(n_classes - 1),
                                   dtype=jnp.float32)

    @staticmethod
    def _regsplex(n: int) -> np.ndarray:
        """n+1 vertices of a regular n-simplex, rows unit-norm, mutual dot
        products equal (reference ``regsplex``)."""
        # host-side precompute in f64 on purpose (norm recurrences lose
        # accuracy in f32); __init__ casts the result to f32 before use
        a = np.zeros((n + 1, n), dtype=np.float64)  # graftlint: disable=GL104
        for k in range(n):
            prior = np.linalg.norm(a[k, :k])
            a[k, k] = 1.0 if k == 0 else np.sqrt(1.0 - prior * prior)
            c = (a[k, k] ** 2 - 1.0 - 1.0 / n) / a[k, k]
            a[k + 1:, k] = c
        return a

    def apply(self, input, target):
        t = target.astype(jnp.int32).reshape(-1)
        emb = jnp.zeros((t.shape[0], self.n_classes), input.dtype)
        emb = emb.at[:, : self.n_classes - 1].set(self.simplex[t])
        return jnp.mean((input - emb) ** 2)


class SmoothL1CriterionWithWeights(Criterion):
    """Fast-RCNN bbox loss with inside/outside weights and sigma
    (reference ``SmoothL1CriterionWithWeights.scala``):
    ``d = (x - t) * w_in``; quadratic inside ``|d| < 1/sigma^2``,
    linear outside, each term scaled by ``w_out``."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        self.sigma2 = sigma * sigma
        self.num = num  # normalizer; 0 = no normalization

    def apply(self, input, target):
        if isinstance(target, (tuple, list)):
            if len(target) == 3:
                gt, w_in, w_out = target
            elif len(target) == 1:
                gt, w_in, w_out = target[0], None, None
            else:
                raise ValueError(
                    "target must be gt or (gt,) or (gt, w_in, w_out); "
                    f"got {len(target)} elements")
        else:
            gt, w_in, w_out = target, None, None
        d = input - gt
        if w_in is not None:
            d = d * w_in
        ad = jnp.abs(d)
        quad = 0.5 * self.sigma2 * d * d
        lin = ad - 0.5 / self.sigma2
        per = jnp.where(ad < 1.0 / self.sigma2, quad, lin)
        if w_out is not None:
            per = per * w_out
        total = jnp.sum(per)
        return total / self.num if self.num > 0 else total


class TimeDistributedMaskCriterion(Criterion):
    """Per-timestep criterion with padding mask (reference
    ``TimeDistributedMaskCriterion.scala``): steps whose target equals
    ``padding_value`` contribute nothing, and the mean runs over valid
    steps only."""

    def __init__(self, criterion: Criterion, padding_value: int = 0):
        self.criterion = criterion
        self.padding_value = padding_value

    def apply(self, input, target):
        N, T = target.shape[0], target.shape[1]
        flat_in = input.reshape((N * T,) + input.shape[2:])
        flat_t = target.reshape((N * T,) + target.shape[2:])
        valid = (flat_t != self.padding_value).reshape(N * T, -1).all(axis=-1)

        inner = self.criterion

        def one(x, t):
            return inner.apply(x[None], t[None])

        per = jax.vmap(one)(flat_in, flat_t)
        total = jnp.sum(jnp.where(valid, per, 0.0))
        return total / jnp.maximum(jnp.sum(valid), 1)


class TransformerCriterion(Criterion):
    """Apply a module to input and/or target, then a criterion (reference
    ``TransformerCriterion.scala`` — e.g. perceptual losses where both go
    through a feature extractor)."""

    def __init__(self, criterion: Criterion,
                 input_transformer: Optional[Module] = None,
                 target_transformer: Optional[Module] = None):
        self.criterion = criterion
        self.input_transformer = input_transformer
        self.target_transformer = target_transformer

    @staticmethod
    def _run(mod: Optional[Module], x):
        if mod is None:
            return x
        # read the module's current params every call — weights loaded or
        # trained into the transformer after construction must take effect
        mod._ensure_init()
        out, _ = mod.apply(mod._params, mod._state, x, training=False)
        return out

    def apply(self, input, target):
        xi = self._run(self.input_transformer, input)
        xt = self._run(self.target_transformer, target)
        return self.criterion.apply(xi, xt)


class CategoricalCrossEntropy(Criterion):
    """Keras ``categorical_crossentropy`` contract (probability inputs,
    one-hot **or** integer class targets) — the loss Keras-ported scripts
    expect (reference ``pyspark/bigdl/keras/converter.py`` loss mapping).

    ``log_prob_input=True`` treats the input as log-probabilities
    (pair with LogSoftMax) instead of probabilities (pair with SoftMax).
    """

    def __init__(self, log_prob_input: bool = False, eps: float = 1e-7):
        self.log_prob_input = log_prob_input
        self.eps = eps

    def apply(self, input, target):
        logp = input if self.log_prob_input else \
            jnp.log(jnp.clip(input, self.eps, 1.0))
        if target.ndim == input.ndim:  # one-hot / soft targets
            return -jnp.mean(jnp.sum(target * logp, axis=-1))
        t = target.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]
        return -jnp.mean(picked)
