"""Extended spatial layers: dilated/separable/locally-connected convs,
LRN + classic normalizations, spatial dropouts, up/down-sampling, crops.

Reference files (all under ``DL/nn/``): ``SpatialDilatedConvolution.scala``,
``SpatialSeparableConvolution.scala``, ``SpatialShareConvolution.scala``,
``SpatialConvolutionMap.scala``, ``LocallyConnected1D/2D.scala``,
``SpatialWithinChannelLRN.scala``, ``SpatialSubtractiveNormalization.scala``,
``SpatialDivisiveNormalization.scala``, ``SpatialContrastiveNormalization
.scala``, ``SpatialDropout1D/2D/3D.scala``, ``UpSampling1D/2D/3D.scala``,
``ResizeBilinear.scala``, ``Cropping2D/3D.scala``, ``TemporalMaxPooling
.scala``.

All NCHW (batch, channel, ...) like the reference; each layer is a thin
``lax``/``jnp`` program — no hand-written backward (jax.grad).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.layers import SpatialConvolution
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform


class SpatialDilatedConvolution(SpatialConvolution):
    """Dilated 2-D conv (reference ``SpatialDilatedConvolution.scala``) —
    the base conv already supports ``rhs_dilation``; the reference keeps a
    separate class, mirrored here for script parity."""

    def __init__(self, n_input_plane, n_output_plane, kw, kh,
                 dw=1, dh=1, pad_w=0, pad_h=0, dilation_w=1, dilation_h=1,
                 **kwargs):
        super().__init__(n_input_plane, n_output_plane, kw, kh, dw, dh,
                         pad_w, pad_h, dilation_w=dilation_w,
                         dilation_h=dilation_h, **kwargs)


class SpatialShareConvolution(SpatialConvolution):
    """Reference ``SpatialShareConvolution.scala`` shares im2col buffers
    across replicas — a JVM memory optimization with no XLA analog (XLA
    owns buffers); computationally identical to SpatialConvolution."""
    pass


class SpatialSeparableConvolution(Module):
    """Depthwise conv × depth multiplier, then 1×1 pointwise (reference
    ``SpatialSeparableConvolution.scala``)."""

    def __init__(self, n_input_channel: int, n_output_channel: int,
                 depth_multiplier: int, kw: int, kh: int,
                 sw: int = 1, sh: int = 1, pw: int = 0, ph: int = 0,
                 with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_channel
        self.n_output = n_output_channel
        self.mult = depth_multiplier
        self.kernel = (kh, kw)
        self.stride = (sh, sw)
        self.pad = (ph, pw)
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        kh, kw = self.kernel
        mid = self.n_input * self.mult
        params = {
            # depthwise: (mult*in, 1, kh, kw) with groups=in
            "depth_weight": self.weight_init.init(
                k1, (mid, 1, kh, kw), kh * kw, self.mult * kh * kw),
            "point_weight": self.weight_init.init(
                k2, (self.n_output, mid, 1, 1), mid, self.n_output),
        }
        if self.with_bias:
            params["bias"] = jnp.zeros((self.n_output,), jnp.float32)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        ph, pw = self.pad
        y = lax.conv_general_dilated(
            input, params["depth_weight"], self.stride,
            ((ph, ph), (pw, pw)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_input)
        y = lax.conv_general_dilated(
            y, params["point_weight"], (1, 1), ((0, 0), (0, 0)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if self.with_bias:
            y = y + params["bias"][None, :, None, None]
        return y, state


class SpatialConvolutionMap(Module):
    """Conv with an explicit input→output connection table (reference
    ``SpatialConvolutionMap.scala``; LeNet-style partial connectivity).

    ``conn_table``: int array (n_connections, 2) of (input_plane,
    output_plane) pairs, 0-based.  Implemented as a dense conv with a
    constant 0/1 mask on the weight — XLA folds the mask; semantics match
    the reference's per-connection accumulation exactly."""

    def __init__(self, conn_table, kw: int, kh: int, dw: int = 1,
                 dh: int = 1, pad_w: int = 0, pad_h: int = 0,
                 weight_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        import numpy as np
        tbl = np.asarray(conn_table, int)
        self.conn_table = tbl
        self.n_input = int(tbl[:, 0].max()) + 1
        self.n_output = int(tbl[:, 1].max()) + 1
        self.kernel = (kh, kw)
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        self.weight_init = weight_init or RandomUniform()
        mask = np.zeros((self.n_output, self.n_input, 1, 1), np.float32)
        mask[tbl[:, 1], tbl[:, 0]] = 1.0
        self._mask = mask

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input * kh * kw
        w = self.weight_init.init(
            k_w, (self.n_output, self.n_input, kh, kw), fan_in, fan_in)
        return {"weight": w * self._mask,
                "bias": jnp.zeros((self.n_output,), jnp.float32)}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        ph, pw = self.pad
        y = lax.conv_general_dilated(
            input, params["weight"] * self._mask, self.stride,
            ((ph, ph), (pw, pw)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return y + params["bias"][None, :, None, None], state


def _extract_patches(x, kh, kw, sh, sw, ph, pw):
    """(N, C, H, W) → (N, C*kh*kw, oh, ow) im2col via XLA patches."""
    return lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), ((ph, ph), (pw, pw)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


class LocallyConnected2D(Module):
    """Conv with UNSHARED weights per output location (reference
    ``LocallyConnected2D.scala``).  Implemented as im2col patches +
    einsum over per-position kernels."""

    def __init__(self, n_input_plane: int, input_width: int,
                 input_height: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.in_hw = (input_height, input_width)
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()
        self.out_hw = tuple(
            (self.in_hw[i] + 2 * self.pad[i] - self.kernel[i])
            // self.stride[i] + 1 for i in (0, 1))

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        kh, kw = self.kernel
        oh, ow = self.out_hw
        fan_in = self.n_input * kh * kw
        params = {"weight": self.weight_init.init(
            k_w, (oh, ow, self.n_output, self.n_input * kh * kw),
            fan_in, self.n_output)}
        if self.with_bias:
            params["bias"] = jnp.zeros((self.n_output, oh, ow), jnp.float32)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        patches = _extract_patches(input, kh, kw, sh, sw, ph, pw)
        # patches: (N, C*kh*kw, oh, ow); weight: (oh, ow, O, C*kh*kw)
        y = jnp.einsum("nkhw,hwok->nohw", patches, params["weight"])
        if self.with_bias:
            y = y + params["bias"][None]
        return y, state


class LocallyConnected1D(Module):
    """1-D locally-connected layer over (N, T, C) sequences (reference
    ``LocallyConnected1D.scala``)."""

    def __init__(self, n_input_frame: int, input_frame_size: int,
                 output_frame_size: int, kernel_w: int, stride_w: int = 1,
                 with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input_frame = n_input_frame
        self.in_size = input_frame_size
        self.out_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()
        self.n_output_frame = (n_input_frame - kernel_w) // stride_w + 1

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.in_size * self.kernel_w
        params = {"weight": self.weight_init.init(
            k_w, (self.n_output_frame, self.out_size,
                  self.kernel_w * self.in_size), fan_in, self.out_size)}
        if self.with_bias:
            params["bias"] = jnp.zeros(
                (self.n_output_frame, self.out_size), jnp.float32)
        return params, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        # input (N, T, C) → windows (N, oT, kw*C)
        idx = (jnp.arange(self.n_output_frame)[:, None] * self.stride_w
               + jnp.arange(self.kernel_w)[None, :])
        win = input[:, idx]  # (N, oT, kw, C)
        win = win.reshape(win.shape[0], self.n_output_frame, -1)
        y = jnp.einsum("ntk,tok->nto", win, params["weight"])
        if self.with_bias:
            y = y + params["bias"][None]
        return y, state


class SpatialWithinChannelLRN(Module):
    """LRN over a spatial window within each channel (reference
    ``SpatialWithinChannelLRN.scala``):
    ``y = x / (1 + alpha/size^2 * avgpool(x^2, size))^beta``."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, name: Optional[str] = None):
        super().__init__(name)
        assert size % 2 == 1, "LRN size must be odd"
        self.size = size
        self.alpha = alpha
        self.beta = beta

    def apply(self, params, state, input, *, training=False, rng=None):
        s = self.size
        p = s // 2
        sq = input * input
        summed = lax.reduce_window(
            sq, 0.0, lax.add, (1, 1, s, s), (1, 1, 1, 1),
            ((0, 0), (0, 0), (p, p), (p, p)))
        denom = (1.0 + (self.alpha / (s * s)) * summed) ** self.beta
        return input / denom, state


def _gaussian_kernel2d(size: int, sigma: float = None):
    import numpy as np
    if sigma is None:
        sigma = 0.3 * ((size - 1) * 0.5 - 1) + 0.8
    ax = np.arange(size) - (size - 1) / 2.0
    g = np.exp(-(ax ** 2) / (2 * sigma ** 2))
    k = np.outer(g, g)
    return (k / k.sum()).astype(np.float32)


class SpatialSubtractiveNormalization(Module):
    """Subtract a weighted neighbourhood mean (reference
    ``SpatialSubtractiveNormalization.scala``; default kernel = gaussian).
    The kernel is averaged across input channels like the reference."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_plane
        import numpy as np
        k = _gaussian_kernel2d(9) if kernel is None \
            else np.asarray(kernel, np.float32)
        k = k / (k.sum() * n_input_plane)
        self._kernel = k

    def _local_mean(self, input):
        kh, kw = self._kernel.shape
        ph, pw = kh // 2, kw // 2
        w = jnp.asarray(self._kernel)[None, None].repeat(self.n_input, 1)
        mean = lax.conv_general_dilated(
            input, w, (1, 1), ((ph, ph), (pw, pw)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # normalize by the actually-covered coefficient sum at borders
        ones = jnp.ones((1, self.n_input) + input.shape[2:], input.dtype)
        coef = lax.conv_general_dilated(
            ones, w, (1, 1), ((ph, ph), (pw, pw)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return mean / coef

    def apply(self, params, state, input, *, training=False, rng=None):
        return input - self._local_mean(input), state


class SpatialDivisiveNormalization(SpatialSubtractiveNormalization):
    """Divide by the neighbourhood standard deviation (reference
    ``SpatialDivisiveNormalization.scala``); thresholded at the global
    mean std like the reference."""

    def apply(self, params, state, input, *, training=False, rng=None):
        local_std = jnp.sqrt(jnp.maximum(
            self._local_mean(input * input), 0.0))
        mean_std = jnp.mean(local_std, axis=(2, 3), keepdims=True)
        denom = jnp.maximum(local_std, mean_std)
        denom = jnp.where(denom < 1e-8, 1.0, denom)
        return input / denom, state


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization (reference
    ``SpatialContrastiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel)

    def apply(self, params, state, input, *, training=False, rng=None):
        y, _ = self.sub.apply({}, {}, input, training=training)
        y, _ = self.div.apply({}, {}, y, training=training)
        return y, state


class _ChannelDropout(Module):
    """Drop whole feature maps (keeps XLA shapes static; scaling matches
    torch SpatialDropout — NO 1/p rescale in the reference, which follows
    Torch's nn.SpatialDropout: masks only)."""

    axes_after_channel: int = 2

    def __init__(self, init_p: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.p = init_p

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.p == 0.0:
            return input, state
        if rng is None:
            raise ValueError(f"{self.name}: training needs rng")
        mask_shape = input.shape[:2] + (1,) * (input.ndim - 2)
        keep = jax.random.bernoulli(rng, 1.0 - self.p, mask_shape)
        return input * keep.astype(input.dtype), state


class SpatialDropout1D(_ChannelDropout):
    """(N, T, C): drops channels (last dim), reference
    ``SpatialDropout1D.scala``."""

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.p == 0.0:
            return input, state
        if rng is None:
            raise ValueError(f"{self.name}: training needs rng")
        mask_shape = (input.shape[0], 1, input.shape[2])
        keep = jax.random.bernoulli(rng, 1.0 - self.p, mask_shape)
        return input * keep.astype(input.dtype), state


class SpatialDropout2D(_ChannelDropout):
    """(N, C, H, W), reference ``SpatialDropout2D.scala``."""
    pass


class SpatialDropout3D(_ChannelDropout):
    """(N, C, D, H, W), reference ``SpatialDropout3D.scala``."""
    pass


class UpSampling1D(Module):
    """Repeat each timestep ``length`` times, (N, T, C) (reference
    ``UpSampling1D.scala``)."""

    def __init__(self, length: int = 2, name=None):
        super().__init__(name)
        self.length = length

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.repeat(input, self.length, axis=1), state


class UpSampling2D(Module):
    """Nearest-neighbour upsample (N, C, H, W) (reference
    ``UpSampling2D.scala``)."""

    def __init__(self, size: Sequence[int] = (2, 2), name=None):
        super().__init__(name)
        self.size = tuple(size)

    def apply(self, params, state, input, *, training=False, rng=None):
        y = jnp.repeat(input, self.size[0], axis=2)
        return jnp.repeat(y, self.size[1], axis=3), state


class UpSampling3D(Module):
    """(N, C, D, H, W) nearest upsample (reference ``UpSampling3D.scala``)."""

    def __init__(self, size: Sequence[int] = (2, 2, 2), name=None):
        super().__init__(name)
        self.size = tuple(size)

    def apply(self, params, state, input, *, training=False, rng=None):
        y = input
        for ax, s in zip((2, 3, 4), self.size):
            y = jnp.repeat(y, s, axis=ax)
        return y, state


class ResizeBilinear(Module):
    """Bilinear resize of NCHW images (reference ``ResizeBilinear.scala``;
    align_corners supported)."""

    def __init__(self, out_height: int, out_width: int,
                 align_corners: bool = False, name=None):
        super().__init__(name)
        self.out_hw = (out_height, out_width)
        self.align_corners = align_corners

    def apply(self, params, state, input, *, training=False, rng=None):
        n, c, h, w = input.shape
        oh, ow = self.out_hw
        if self.align_corners and oh > 1 and ow > 1:
            ys = jnp.linspace(0.0, h - 1.0, oh)
            xs = jnp.linspace(0.0, w - 1.0, ow)
        else:
            # half-pixel-free TF1 semantics like the reference:
            # src = dst * scale
            ys = jnp.arange(oh) * (h / oh)
            xs = jnp.arange(ow) * (w / ow)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0).astype(input.dtype)
        wx = (xs - x0).astype(input.dtype)
        a = input[:, :, y0][:, :, :, x0]
        b = input[:, :, y0][:, :, :, x1]
        c_ = input[:, :, y1][:, :, :, x0]
        d = input[:, :, y1][:, :, :, x1]
        wy = wy[None, None, :, None]
        wx = wx[None, None, None, :]
        top = a * (1 - wx) + b * wx
        bot = c_ * (1 - wx) + d * wx
        return top * (1 - wy) + bot * wy, state


class Cropping2D(Module):
    """Crop rows/cols off a (N, C, H, W) tensor (reference
    ``Cropping2D.scala``)."""

    def __init__(self, height_crop=(0, 0), width_crop=(0, 0), name=None):
        super().__init__(name)
        self.hc = tuple(height_crop)
        self.wc = tuple(width_crop)

    def apply(self, params, state, input, *, training=False, rng=None):
        h, w = input.shape[2], input.shape[3]
        return input[:, :, self.hc[0]:h - self.hc[1],
                     self.wc[0]:w - self.wc[1]], state


class Cropping3D(Module):
    """Crop a (N, C, D, H, W) tensor (reference ``Cropping3D.scala``)."""

    def __init__(self, dim1_crop=(0, 0), dim2_crop=(0, 0),
                 dim3_crop=(0, 0), name=None):
        super().__init__(name)
        self.crops = (tuple(dim1_crop), tuple(dim2_crop), tuple(dim3_crop))

    def apply(self, params, state, input, *, training=False, rng=None):
        d, h, w = input.shape[2:]
        (d0, d1), (h0, h1), (w0, w1) = self.crops
        return input[:, :, d0:d - d1, h0:h - h1, w0:w - w1], state


class TemporalMaxPooling(Module):
    """1-D max pooling over (N, T, C) (reference
    ``TemporalMaxPooling.scala``)."""

    def __init__(self, k_w: int, d_w: Optional[int] = None, name=None):
        super().__init__(name)
        self.k = k_w
        self.d = d_w or k_w

    def apply(self, params, state, input, *, training=False, rng=None):
        y = lax.reduce_window(
            input, -jnp.inf, lax.max, (1, self.k, 1), (1, self.d, 1),
            ((0, 0), (0, 0), (0, 0)))
        return y, state
