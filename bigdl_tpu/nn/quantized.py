"""Post-training int8 quantization.

Reference: ``DL/nn/quantized/Quantization.scala`` (``model.quantize()``
converts Linear/SpatialConvolution/… to quantized twins) +
``quantized/Linear.scala:79-90`` (BigQuant mixed-precision GEMM: int8
weights per-output-channel, activations quantized on the fly, int32
accumulate, dequantize).

TPU redesign (SURVEY §7 stage 9): the BigQuant JNI kernels become
``lax.dot_general``/``lax.conv_general_dilated`` on int8 operands with
``preferred_element_type=int32`` — XLA lowers that onto the MXU's int8
path natively.  Scheme matches the reference's:

- weights: symmetric per-output-channel int8
  (``scale_o = max|W_o| / 127``);
- activations: symmetric per-tensor dynamic int8, the max computed on the
  fly per batch exactly like BigQuant's runtime quantization;
- accumulation int32, dequantize with ``x_scale * w_scale_o``, add the
  f32 bias.
"""

from __future__ import annotations

import copy
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.layers import Linear, SpatialConvolution, _conv_dims
from bigdl_tpu.nn.module import Container, Module


def _quantize_symmetric(w: np.ndarray, axis=None):
    """Return (int8 values, f32 scale) with symmetric range mapping."""
    amax = np.max(np.abs(w), axis=axis, keepdims=axis is not None)
    scale = np.maximum(amax, 1e-8) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, np.asarray(scale, np.float32)


def _dyn_quantize(x: jnp.ndarray):
    """Per-tensor dynamic activation quantization (traced; scale is a
    runtime value like BigQuant's on-the-fly quantization)."""
    amax = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    scale = amax / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


class QuantizedLinear(Module):
    """int8 Linear (reference ``quantized/Linear.scala``)."""

    def __init__(self, weight_q: np.ndarray, weight_scale: np.ndarray,
                 bias: Optional[np.ndarray], name: Optional[str] = None):
        super().__init__(name)
        self.weight_q = jnp.asarray(weight_q)          # (out, in) int8
        self.weight_scale = jnp.asarray(weight_scale)  # (out, 1)
        self.bias = None if bias is None else jnp.asarray(bias)

    @staticmethod
    def from_linear(m: Linear, params) -> "QuantizedLinear":
        wq, ws = _quantize_symmetric(np.asarray(params["weight"]), axis=1)
        b = np.asarray(params["bias"]) if "bias" in params else None
        return QuantizedLinear(wq, ws, b, name=m.name)

    def init(self, rng):
        return {}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        xq, xs = _dyn_quantize(input)
        acc = lax.dot_general(
            xq, self.weight_q.T,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (xs * self.weight_scale[:, 0][None])
        if self.bias is not None:
            y = y + self.bias
        return y, state


class QuantizedSpatialConvolution(Module):
    """int8 conv (reference ``quantized/SpatialConvolution.scala``)."""

    def __init__(self, conv: SpatialConvolution, weight_q, weight_scale,
                 bias, name: Optional[str] = None):
        super().__init__(name or conv.name)
        self.conv = conv
        self.weight_q = jnp.asarray(weight_q)          # OIHW int8
        self.weight_scale = jnp.asarray(weight_scale)  # (O,1,1,1)
        self.bias = None if bias is None else jnp.asarray(bias)

    @staticmethod
    def from_conv(m: SpatialConvolution, params
                  ) -> "QuantizedSpatialConvolution":
        wq, ws = _quantize_symmetric(np.asarray(params["weight"]),
                                     axis=(1, 2, 3))
        b = np.asarray(params["bias"]) if "bias" in params else None
        return QuantizedSpatialConvolution(m, wq, ws, b)

    def init(self, rng):
        return {}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        m = self.conv
        xq, xs = _dyn_quantize(input)
        w = self.weight_q
        if m.format == "NHWC":
            w = jnp.transpose(w, (2, 3, 1, 0))
        ph, pw_ = m.pad
        padding = "SAME" if (ph == -1 or pw_ == -1) else ((ph, ph),
                                                          (pw_, pw_))
        acc = lax.conv_general_dilated(
            xq, w, window_strides=m.stride, padding=padding,
            rhs_dilation=m.dilation,
            dimension_numbers=_conv_dims(m.format),
            feature_group_count=m.n_group,
            preferred_element_type=jnp.int32)
        ws = self.weight_scale.reshape(-1)
        if m.format == "NCHW":
            y = acc.astype(jnp.float32) * (xs * ws)[None, :, None, None]
            if self.bias is not None:
                y = y + self.bias[None, :, None, None]
        else:
            y = acc.astype(jnp.float32) * (xs * ws)[None, None, None, :]
            if self.bias is not None:
                y = y + self.bias[None, None, None, :]
        return y, state


def quantize(model: Module) -> Module:
    """Post-training quantization of a materialized (eager) module tree —
    the ``model.quantize()`` entry point (reference
    ``Quantization.quantize``).  Returns a NEW module; the original is
    untouched.  Linear/SpatialConvolution become int8; everything else is
    kept (running on f32 activations exactly like the reference's mixed
    graph)."""
    model._ensure_init()

    def convert(m: Module, params) -> Module:
        if isinstance(m, Container):
            out = copy.copy(m)
            out.modules = [convert(c, params.get(str(i), {}))
                           for i, c in enumerate(m.modules)]
            return out
        if isinstance(m, Linear):
            return QuantizedLinear.from_linear(m, params)
        if isinstance(m, SpatialConvolution) and type(m) is \
                SpatialConvolution:
            return QuantizedSpatialConvolution.from_conv(m, params)
        return m

    q = convert(model, model._params)

    # rebuild eager params/state for the converted tree: quantized leaves
    # carry their buffers on the object, so init() gives empty params there
    # while untouched leaves keep their trained params
    def rebuild(m: Module, params, state):
        if isinstance(m, Container):
            p, s = {}, {}
            for i, c in enumerate(m.modules):
                cp, cs = rebuild(c, params.get(str(i), {}),
                                 state.get(str(i), {}))
                p[str(i)], s[str(i)] = cp, cs
            return p, s
        if isinstance(m, (QuantizedLinear, QuantizedSpatialConvolution)):
            return {}, {}
        return params, state

    q._params, q._state = rebuild(q, model._params, model._state)
    q._grads = jax.tree_util.tree_map(jnp.zeros_like, q._params)
    q.training = False
    return q
