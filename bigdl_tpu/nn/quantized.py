"""Post-training int8 quantization.

Reference: ``DL/nn/quantized/Quantization.scala`` (``model.quantize()``
converts Linear/SpatialConvolution/… to quantized twins) +
``quantized/Linear.scala:79-90`` (BigQuant mixed-precision GEMM: int8
weights per-output-channel, activations quantized on the fly, int32
accumulate, dequantize).

TPU redesign (SURVEY §7 stage 9, reworked in the int8 speed-path PR):
the BigQuant JNI kernels become the fused Pallas mixed-precision GEMM
in ``ops/pallas_int8_gemm.py`` — int8 weight panel VMEM-resident,
per-output-channel f32 scales, dequantize + bias fused in-register —
behind the standard ``kernel_impl`` gate with a bitwise-identical XLA
fallback.  Scheme still matches the reference's:

- weights: symmetric per-output-channel int8
  (``scale_o = max|W_o| / 127``);
- activations, per-layer ``mode`` (``Config.int8_activation_mode``
  default, ``quantize(model, mode=...)`` override):

  - ``"weight_only"``: keep f32/bf16 activations, f32 MXU accumulation
    against the int8 panel — no activation quantization error; the
    serving default (the weight panel bytes are what small-batch
    inference pays for);
  - ``"dynamic"``: symmetric per-tensor int8 on the fly exactly like
    BigQuant's runtime quantization, int32 accumulate, dequantize with
    ``x_scale * w_scale_o``;

- f32 bias added after dequantization either way.

``QuantizedSpatialConvolution`` reduces onto the same GEMM (1x1
reshape / im2col patches) when ``n_group == 1`` and the kernel's
``supported()`` gate passes; otherwise it keeps the direct
``lax.conv_general_dilated`` simulation (mode-aware).  Conversion
semantics and pytree/exporter traversal are unchanged — quantized
leaves still carry their buffers on the object and ``init()`` returns
empty params.
"""

from __future__ import annotations

import copy
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.layers import Linear, SpatialConvolution, _conv_dims
from bigdl_tpu.nn.module import Container, Module
from bigdl_tpu.ops import pallas_int8_gemm
from bigdl_tpu.ops.pallas_int8_gemm import MODES, int8_matmul

# activation quantization lives with the kernel now (single definition
# shared by kernel body and fallback); this alias keeps the historical
# nn.quantized surface working
_dyn_quantize = pallas_int8_gemm.dyn_quantize


def _default_mode(mode: Optional[str]) -> str:
    """Resolve the per-layer activation mode: explicit arg >
    ``Config.int8_activation_mode`` (env ``BIGDL_TPU_INT8_ACTIVATION_
    MODE``) > the "weight_only" dataclass default."""
    if mode is None:
        from bigdl_tpu.utils.config import get_config
        mode = get_config().int8_activation_mode
    if mode not in MODES:
        raise ValueError(
            f"int8 activation mode must be one of {MODES}, got {mode!r}")
    return mode


def _quantize_symmetric(w: np.ndarray, axis=None):
    """Return (int8 values, f32 scale) with symmetric range mapping."""
    amax = np.max(np.abs(w), axis=axis, keepdims=axis is not None)
    scale = np.maximum(amax, 1e-8) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, np.asarray(scale, np.float32)


def _int8_linear(x, wq, wscale, bias=None, *, mode: str = "weight_only",
                 impl=None):
    """``x @ W.T + b`` through the kernel-backed quantized GEMM
    (``ops/pallas_int8_gemm.int8_matmul`` — pallas where supported,
    bitwise-identical XLA fallback otherwise)."""
    return int8_matmul(x, wq, wscale, bias, mode=mode, impl=impl)


class QuantizedLinear(Module):
    """int8 Linear (reference ``quantized/Linear.scala``)."""

    def __init__(self, weight_q: np.ndarray, weight_scale: np.ndarray,
                 bias: Optional[np.ndarray], name: Optional[str] = None,
                 mode: Optional[str] = None, impl: Optional[str] = None):
        super().__init__(name)
        self.weight_q = jnp.asarray(weight_q)          # (out, in) int8
        self.weight_scale = jnp.asarray(weight_scale)  # (out, 1)
        self.bias = None if bias is None else jnp.asarray(bias)
        self.mode = _default_mode(mode)
        self.impl = impl

    @staticmethod
    def from_linear(m: Linear, params, mode: Optional[str] = None,
                    impl: Optional[str] = None) -> "QuantizedLinear":
        wq, ws = _quantize_symmetric(np.asarray(params["weight"]), axis=1)
        b = np.asarray(params["bias"]) if "bias" in params else None
        return QuantizedLinear(wq, ws, b, name=m.name, mode=mode,
                               impl=impl)

    def init(self, rng):
        return {}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        return _int8_linear(input, self.weight_q, self.weight_scale,
                            self.bias, mode=self.mode,
                            impl=self.impl), state


class QuantizedSpatialConvolution(Module):
    """int8 conv (reference ``quantized/SpatialConvolution.scala``).

    Reduces onto the shared int8 GEMM — a 1x1 kernel is a plain
    reshape, anything else goes through im2col
    (``lax.conv_general_dilated_patches``) — whenever ``n_group == 1``,
    the resolved ``kernel_impl`` is pallas and the flattened
    (C*kh*kw, O) panel passes the GEMM's ``supported()`` gate.  All
    other shapes keep the direct ``lax.conv_general_dilated``
    simulation with the same per-mode quantized math.
    """

    def __init__(self, conv: SpatialConvolution, weight_q, weight_scale,
                 bias, name: Optional[str] = None,
                 mode: Optional[str] = None, impl: Optional[str] = None):
        super().__init__(name or conv.name)
        self.conv = conv
        self.weight_q = jnp.asarray(weight_q)          # OIHW int8
        self.weight_scale = jnp.asarray(weight_scale)  # (O,1,1,1)
        self.bias = None if bias is None else jnp.asarray(bias)
        self.mode = _default_mode(mode)
        self.impl = impl

    @staticmethod
    def from_conv(m: SpatialConvolution, params,
                  mode: Optional[str] = None, impl: Optional[str] = None
                  ) -> "QuantizedSpatialConvolution":
        wq, ws = _quantize_symmetric(np.asarray(params["weight"]),
                                     axis=(1, 2, 3))
        b = np.asarray(params["bias"]) if "bias" in params else None
        return QuantizedSpatialConvolution(m, wq, ws, b, mode=mode,
                                           impl=impl)

    def init(self, rng):
        return {}, {}

    def _padding(self):
        ph, pw_ = self.conv.pad
        return "SAME" if (ph == -1 or pw_ == -1) else ((ph, ph),
                                                       (pw_, pw_))

    def _gemm_engages(self, batch_hint: int, x_dtype) -> bool:
        """Host-side (trace-time) decision: route through the GEMM only
        when the pallas kernel would actually engage — the im2col
        reshuffle is pure overhead in front of an XLA fallback."""
        from bigdl_tpu.ops import resolve_kernel_impl
        m = self.conv
        if m.n_group != 1:
            return False
        if resolve_kernel_impl(self.impl) != "pallas":
            return False
        O, C, kh, kw = self.weight_q.shape
        return pallas_int8_gemm.supported(max(batch_hint, 1), C * kh * kw,
                                          O, x_dtype, self.mode)

    def _apply_gemm(self, x):
        """im2col / 1x1 reduction onto the shared int8 GEMM."""
        m = self.conv
        O, C, kh, kw = self.weight_q.shape
        patches = lax.conv_general_dilated_patches(
            x, filter_shape=(kh, kw), window_strides=m.stride,
            padding=self._padding(), rhs_dilation=m.dilation,
            dimension_numbers=_conv_dims(m.format))
        # patches put the C*kh*kw unrolled taps in the spec's feature
        # dim (channel-major, matching OIHW.reshape(O, -1) flattening)
        if m.format == "NCHW":
            n, k, ho, wo = patches.shape
            rows = jnp.transpose(patches, (0, 2, 3, 1)).reshape(-1, k)
        else:
            n, ho, wo, k = patches.shape
            rows = patches.reshape(-1, k)
        y = int8_matmul(rows, self.weight_q.reshape(O, -1),
                        self.weight_scale, self.bias, mode=self.mode,
                        impl=self.impl)
        y = y.reshape(n, ho, wo, O)
        if m.format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def _apply_sim(self, x):
        """Direct ``lax.conv_general_dilated`` simulation of the same
        quantized math (the pre-kernel path, kept for grouped convs and
        shapes the GEMM gate rejects)."""
        m = self.conv
        w = self.weight_q
        if m.format == "NHWC":
            w = jnp.transpose(w, (2, 3, 1, 0))
        padding = self._padding()
        ws = self.weight_scale.reshape(-1)
        if self.mode == "dynamic":
            xq, xs = _dyn_quantize(x)
            acc = lax.conv_general_dilated(
                xq, w, window_strides=m.stride, padding=padding,
                rhs_dilation=m.dilation,
                dimension_numbers=_conv_dims(m.format),
                feature_group_count=m.n_group,
                preferred_element_type=jnp.int32)
            scale = xs * ws
        else:  # weight_only: f32 accumulation, no activation error
            acc = lax.conv_general_dilated(
                x.astype(jnp.float32), w.astype(jnp.float32),
                window_strides=m.stride, padding=padding,
                rhs_dilation=m.dilation,
                dimension_numbers=_conv_dims(m.format),
                feature_group_count=m.n_group,
                preferred_element_type=jnp.float32)
            scale = ws
        if m.format == "NCHW":
            y = acc.astype(jnp.float32) * scale[None, :, None, None]
            if self.bias is not None:
                y = y + self.bias[None, :, None, None]
        else:
            y = acc.astype(jnp.float32) * scale[None, None, None, :]
            if self.bias is not None:
                y = y + self.bias[None, None, None, :]
        return y

    def apply(self, params, state, input, *, training=False, rng=None):
        if self._gemm_engages(input.shape[0], input.dtype):
            return self._apply_gemm(input), state
        return self._apply_sim(input), state


# --------------------------------------------------- quantized recurrent
# (reference Quantization.quantize also converts the recurrent cells —
# "Linear/SpatialConvolution/gru etc", SURVEY §2.2 quantized row; the
# cells' fused gate projections are exactly the BigQuant GEMM shape)
class _QuantizedCellBase(Module):
    """Module subclass so spec_children tree-walkers (regularizers,
    sharding specs, exporters) traverse quantized cells like any leaf."""

    def __init__(self, cell, mode: Optional[str] = None,
                 impl: Optional[str] = None):
        super().__init__(f"Quantized{type(cell).__name__}")
        self.cell = cell
        self.hidden_size = cell.hidden_size
        self.mode = _default_mode(mode)
        self.impl = impl

    def initial_hidden(self, batch_size):
        return self.cell.initial_hidden(batch_size)

    def init(self, rng):
        return {}, {}

    def _proj(self, x, wq, ws, bias):
        return _int8_linear(x, wq, ws, bias, mode=self.mode,
                            impl=self.impl)


class QuantizedLSTM(_QuantizedCellBase):
    """int8 gate projection LSTM cell."""

    def __init__(self, cell, params, mode: Optional[str] = None,
                 impl: Optional[str] = None):
        super().__init__(cell, mode=mode, impl=impl)
        self.wq, self.ws = _quantize_symmetric(
            np.asarray(params["weight"]), axis=1)
        self.wq = jnp.asarray(self.wq)
        self.ws = jnp.asarray(self.ws)
        self.bias = jnp.asarray(params["bias"])

    def step(self, params, x_t, hidden):
        h, c = hidden
        z = self._proj(jnp.concatenate([x_t, h], axis=-1), self.wq,
                       self.ws, self.bias)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f + self.cell.forget_bias)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class QuantizedGRU(_QuantizedCellBase):
    """int8 gate + candidate projections GRU cell (Keras/reference
    convention: reset applied to h BEFORE the candidate projection)."""

    def __init__(self, cell, params, mode: Optional[str] = None,
                 impl: Optional[str] = None):
        super().__init__(cell, mode=mode, impl=impl)
        self.gq, self.gs = _quantize_symmetric(
            np.asarray(params["w_gates"]), axis=1)
        self.cq, self.cs = _quantize_symmetric(
            np.asarray(params["w_cand"]), axis=1)
        self.gq, self.gs = jnp.asarray(self.gq), jnp.asarray(self.gs)
        self.cq, self.cs = jnp.asarray(self.cq), jnp.asarray(self.cs)
        self.b_gates = jnp.asarray(params["b_gates"])
        self.b_cand = jnp.asarray(params["b_cand"])

    def step(self, params, x_t, h):
        z = self._proj(jnp.concatenate([x_t, h], axis=-1), self.gq,
                       self.gs, self.b_gates)
        r, u = jnp.split(jax.nn.sigmoid(z), 2, axis=-1)
        cand = jnp.tanh(self._proj(
            jnp.concatenate([x_t, r * h], axis=-1), self.cq, self.cs,
            self.b_cand))
        h_new = u * h + (1 - u) * cand
        return h_new, h_new


class QuantizedRnnCell(_QuantizedCellBase):
    """int8 simple RNN cell."""

    def __init__(self, cell, params, mode: Optional[str] = None,
                 impl: Optional[str] = None):
        super().__init__(cell, mode=mode, impl=impl)
        w = np.concatenate([np.asarray(params["w_ih"]),
                            np.asarray(params["w_hh"])], axis=1)
        self.wq, self.ws = _quantize_symmetric(w, axis=1)
        self.wq, self.ws = jnp.asarray(self.wq), jnp.asarray(self.ws)
        self.bias = jnp.asarray(params["bias"])

    def step(self, params, x_t, h):
        z = self._proj(jnp.concatenate([x_t, h], axis=-1), self.wq,
                       self.ws, self.bias)
        h_new = self.cell.activation(z)
        return h_new, h_new


def _quantize_cell(cell, params, mode=None, impl=None):
    from bigdl_tpu.nn.recurrent import GRU, LSTM, RnnCell
    if type(cell) is LSTM:
        return QuantizedLSTM(cell, params, mode=mode, impl=impl)
    if type(cell) is GRU:
        return QuantizedGRU(cell, params, mode=mode, impl=impl)
    if type(cell) is RnnCell:
        return QuantizedRnnCell(cell, params, mode=mode, impl=impl)
    return None


def quantize(model: Module, mode: Optional[str] = None,
             impl: Optional[str] = None) -> Module:
    """Post-training quantization of a materialized (eager) module tree —
    the ``model.quantize()`` entry point (reference
    ``Quantization.quantize``).  Returns a NEW module; the original is
    untouched.  Linear/SpatialConvolution and the LSTM/GRU/RnnCell gate
    projections become int8; everything else is kept (running on f32
    activations exactly like the reference's mixed graph).

    ``mode`` stamps the activation mode on every converted layer
    (``"weight_only"`` / ``"dynamic"``; None = the
    ``Config.int8_activation_mode`` default), ``impl`` the per-layer
    kernel_impl override.  Idempotent: already-quantized leaves are not
    Linear/SpatialConvolution instances, so a second pass keeps them."""
    from bigdl_tpu.nn.recurrent import BiRecurrent, Recurrent
    mode = _default_mode(mode)  # resolve ONCE so the tree is uniform
    model._ensure_init()

    def convert(m: Module, params) -> Module:
        if isinstance(m, Container):
            out = copy.copy(m)
            out.modules = [convert(c, params.get(str(i), {}))
                           for i, c in enumerate(m.modules)]
            return out
        if isinstance(m, Recurrent):
            qc = _quantize_cell(m.cell, params, mode=mode, impl=impl)
            if qc is not None:
                out = copy.copy(m)
                out.cell = qc
                return out
            return m
        if isinstance(m, BiRecurrent):
            out = copy.copy(m)
            out.fwd = convert(m.fwd, params.get("fwd", {}))
            out.bwd = convert(m.bwd, params.get("bwd", {}))
            return out
        if isinstance(m, Linear):
            return QuantizedLinear.from_linear(m, params, mode=mode,
                                               impl=impl)
        if isinstance(m, SpatialConvolution) and type(m) is \
                SpatialConvolution:
            return QuantizedSpatialConvolution.from_conv(m, params,
                                                         mode=mode,
                                                         impl=impl)
        return m

    q = convert(model, model._params)

    # rebuild eager params/state for the converted tree: quantized leaves
    # carry their buffers on the object, so init() gives empty params there
    # while untouched leaves keep their trained params
    def rebuild(m: Module, params, state):
        if isinstance(m, Container):
            p, s = {}, {}
            for i, c in enumerate(m.modules):
                cp, cs = rebuild(c, params.get(str(i), {}),
                                 state.get(str(i), {}))
                p[str(i)], s[str(i)] = cp, cs
            return p, s
        if isinstance(m, Recurrent) \
                and isinstance(m.cell, _QuantizedCellBase):
            return {}, {}
        if isinstance(m, BiRecurrent):
            pf, _ = rebuild(m.fwd, params.get("fwd", {}), {})
            pb, _ = rebuild(m.bwd, params.get("bwd", {}), {})
            return {"fwd": pf, "bwd": pb}, state
        if isinstance(m, (QuantizedLinear, QuantizedSpatialConvolution)):
            return {}, {}
        return params, state

    q._params, q._state = rebuild(q, model._params, model._state)
    q._grads = jax.tree_util.tree_map(jnp.zeros_like, q._params)
    q.training = False
    return q
