"""Post-training int8 quantization.

Reference: ``DL/nn/quantized/Quantization.scala`` (``model.quantize()``
converts Linear/SpatialConvolution/… to quantized twins) +
``quantized/Linear.scala:79-90`` (BigQuant mixed-precision GEMM: int8
weights per-output-channel, activations quantized on the fly, int32
accumulate, dequantize).

TPU redesign (SURVEY §7 stage 9): the BigQuant JNI kernels become
``lax.dot_general``/``lax.conv_general_dilated`` on int8 operands with
``preferred_element_type=int32`` — XLA lowers that onto the MXU's int8
path natively.  Scheme matches the reference's:

- weights: symmetric per-output-channel int8
  (``scale_o = max|W_o| / 127``);
- activations: symmetric per-tensor dynamic int8, the max computed on the
  fly per batch exactly like BigQuant's runtime quantization;
- accumulation int32, dequantize with ``x_scale * w_scale_o``, add the
  f32 bias.
"""

from __future__ import annotations

import copy
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.layers import Linear, SpatialConvolution, _conv_dims
from bigdl_tpu.nn.module import Container, Module


def _quantize_symmetric(w: np.ndarray, axis=None):
    """Return (int8 values, f32 scale) with symmetric range mapping."""
    amax = np.max(np.abs(w), axis=axis, keepdims=axis is not None)
    scale = np.maximum(amax, 1e-8) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, np.asarray(scale, np.float32)


def _dyn_quantize(x: jnp.ndarray):
    """Per-tensor dynamic activation quantization (traced; scale is a
    runtime value like BigQuant's on-the-fly quantization)."""
    amax = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    scale = amax / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _int8_linear(x, wq, wscale, bias=None):
    """Dynamic-int8 ``x @ W.T + b`` on the MXU int8 path."""
    xq, xs = _dyn_quantize(x)
    acc = lax.dot_general(xq, wq.T,
                          dimension_numbers=(((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (xs * wscale.reshape(-1)[None])
    if bias is not None:
        y = y + bias
    return y


class QuantizedLinear(Module):
    """int8 Linear (reference ``quantized/Linear.scala``)."""

    def __init__(self, weight_q: np.ndarray, weight_scale: np.ndarray,
                 bias: Optional[np.ndarray], name: Optional[str] = None):
        super().__init__(name)
        self.weight_q = jnp.asarray(weight_q)          # (out, in) int8
        self.weight_scale = jnp.asarray(weight_scale)  # (out, 1)
        self.bias = None if bias is None else jnp.asarray(bias)

    @staticmethod
    def from_linear(m: Linear, params) -> "QuantizedLinear":
        wq, ws = _quantize_symmetric(np.asarray(params["weight"]), axis=1)
        b = np.asarray(params["bias"]) if "bias" in params else None
        return QuantizedLinear(wq, ws, b, name=m.name)

    def init(self, rng):
        return {}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        return _int8_linear(input, self.weight_q, self.weight_scale,
                            self.bias), state


class QuantizedSpatialConvolution(Module):
    """int8 conv (reference ``quantized/SpatialConvolution.scala``)."""

    def __init__(self, conv: SpatialConvolution, weight_q, weight_scale,
                 bias, name: Optional[str] = None):
        super().__init__(name or conv.name)
        self.conv = conv
        self.weight_q = jnp.asarray(weight_q)          # OIHW int8
        self.weight_scale = jnp.asarray(weight_scale)  # (O,1,1,1)
        self.bias = None if bias is None else jnp.asarray(bias)

    @staticmethod
    def from_conv(m: SpatialConvolution, params
                  ) -> "QuantizedSpatialConvolution":
        wq, ws = _quantize_symmetric(np.asarray(params["weight"]),
                                     axis=(1, 2, 3))
        b = np.asarray(params["bias"]) if "bias" in params else None
        return QuantizedSpatialConvolution(m, wq, ws, b)

    def init(self, rng):
        return {}, {}

    def apply(self, params, state, input, *, training=False, rng=None):
        m = self.conv
        xq, xs = _dyn_quantize(input)
        w = self.weight_q
        if m.format == "NHWC":
            w = jnp.transpose(w, (2, 3, 1, 0))
        ph, pw_ = m.pad
        padding = "SAME" if (ph == -1 or pw_ == -1) else ((ph, ph),
                                                          (pw_, pw_))
        acc = lax.conv_general_dilated(
            xq, w, window_strides=m.stride, padding=padding,
            rhs_dilation=m.dilation,
            dimension_numbers=_conv_dims(m.format),
            feature_group_count=m.n_group,
            preferred_element_type=jnp.int32)
        ws = self.weight_scale.reshape(-1)
        if m.format == "NCHW":
            y = acc.astype(jnp.float32) * (xs * ws)[None, :, None, None]
            if self.bias is not None:
                y = y + self.bias[None, :, None, None]
        else:
            y = acc.astype(jnp.float32) * (xs * ws)[None, None, None, :]
            if self.bias is not None:
                y = y + self.bias[None, None, None, :]
        return y, state


# --------------------------------------------------- quantized recurrent
# (reference Quantization.quantize also converts the recurrent cells —
# "Linear/SpatialConvolution/gru etc", SURVEY §2.2 quantized row; the
# cells' fused gate projections are exactly the BigQuant GEMM shape)
class _QuantizedCellBase(Module):
    """Module subclass so spec_children tree-walkers (regularizers,
    sharding specs, exporters) traverse quantized cells like any leaf."""

    def __init__(self, cell):
        super().__init__(f"Quantized{type(cell).__name__}")
        self.cell = cell
        self.hidden_size = cell.hidden_size

    def initial_hidden(self, batch_size):
        return self.cell.initial_hidden(batch_size)

    def init(self, rng):
        return {}, {}


class QuantizedLSTM(_QuantizedCellBase):
    """int8 gate projection LSTM cell."""

    def __init__(self, cell, params):
        super().__init__(cell)
        self.wq, self.ws = _quantize_symmetric(
            np.asarray(params["weight"]), axis=1)
        self.wq = jnp.asarray(self.wq)
        self.ws = jnp.asarray(self.ws)
        self.bias = jnp.asarray(params["bias"])

    def step(self, params, x_t, hidden):
        h, c = hidden
        z = _int8_linear(jnp.concatenate([x_t, h], axis=-1), self.wq,
                         self.ws, self.bias)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f + self.cell.forget_bias)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class QuantizedGRU(_QuantizedCellBase):
    """int8 gate + candidate projections GRU cell (Keras/reference
    convention: reset applied to h BEFORE the candidate projection)."""

    def __init__(self, cell, params):
        super().__init__(cell)
        self.gq, self.gs = _quantize_symmetric(
            np.asarray(params["w_gates"]), axis=1)
        self.cq, self.cs = _quantize_symmetric(
            np.asarray(params["w_cand"]), axis=1)
        self.gq, self.gs = jnp.asarray(self.gq), jnp.asarray(self.gs)
        self.cq, self.cs = jnp.asarray(self.cq), jnp.asarray(self.cs)
        self.b_gates = jnp.asarray(params["b_gates"])
        self.b_cand = jnp.asarray(params["b_cand"])

    def step(self, params, x_t, h):
        z = _int8_linear(jnp.concatenate([x_t, h], axis=-1), self.gq,
                         self.gs, self.b_gates)
        r, u = jnp.split(jax.nn.sigmoid(z), 2, axis=-1)
        cand = jnp.tanh(_int8_linear(
            jnp.concatenate([x_t, r * h], axis=-1), self.cq, self.cs,
            self.b_cand))
        h_new = u * h + (1 - u) * cand
        return h_new, h_new


class QuantizedRnnCell(_QuantizedCellBase):
    """int8 simple RNN cell."""

    def __init__(self, cell, params):
        super().__init__(cell)
        w = np.concatenate([np.asarray(params["w_ih"]),
                            np.asarray(params["w_hh"])], axis=1)
        self.wq, self.ws = _quantize_symmetric(w, axis=1)
        self.wq, self.ws = jnp.asarray(self.wq), jnp.asarray(self.ws)
        self.bias = jnp.asarray(params["bias"])

    def step(self, params, x_t, h):
        z = _int8_linear(jnp.concatenate([x_t, h], axis=-1), self.wq,
                         self.ws, self.bias)
        h_new = self.cell.activation(z)
        return h_new, h_new


def _quantize_cell(cell, params):
    from bigdl_tpu.nn.recurrent import GRU, LSTM, RnnCell
    if type(cell) is LSTM:
        return QuantizedLSTM(cell, params)
    if type(cell) is GRU:
        return QuantizedGRU(cell, params)
    if type(cell) is RnnCell:
        return QuantizedRnnCell(cell, params)
    return None


def quantize(model: Module) -> Module:
    """Post-training quantization of a materialized (eager) module tree —
    the ``model.quantize()`` entry point (reference
    ``Quantization.quantize``).  Returns a NEW module; the original is
    untouched.  Linear/SpatialConvolution and the LSTM/GRU/RnnCell gate
    projections become int8; everything else is kept (running on f32
    activations exactly like the reference's mixed graph)."""
    from bigdl_tpu.nn.recurrent import BiRecurrent, Recurrent
    model._ensure_init()

    def convert(m: Module, params) -> Module:
        if isinstance(m, Container):
            out = copy.copy(m)
            out.modules = [convert(c, params.get(str(i), {}))
                           for i, c in enumerate(m.modules)]
            return out
        if isinstance(m, Recurrent):
            qc = _quantize_cell(m.cell, params)
            if qc is not None:
                out = copy.copy(m)
                out.cell = qc
                return out
            return m
        if isinstance(m, BiRecurrent):
            out = copy.copy(m)
            out.fwd = convert(m.fwd, params.get("fwd", {}))
            out.bwd = convert(m.bwd, params.get("bwd", {}))
            return out
        if isinstance(m, Linear):
            return QuantizedLinear.from_linear(m, params)
        if isinstance(m, SpatialConvolution) and type(m) is \
                SpatialConvolution:
            return QuantizedSpatialConvolution.from_conv(m, params)
        return m

    q = convert(model, model._params)

    # rebuild eager params/state for the converted tree: quantized leaves
    # carry their buffers on the object, so init() gives empty params there
    # while untouched leaves keep their trained params
    def rebuild(m: Module, params, state):
        if isinstance(m, Container):
            p, s = {}, {}
            for i, c in enumerate(m.modules):
                cp, cs = rebuild(c, params.get(str(i), {}),
                                 state.get(str(i), {}))
                p[str(i)], s[str(i)] = cp, cs
            return p, s
        if isinstance(m, Recurrent) \
                and isinstance(m.cell, _QuantizedCellBase):
            return {}, {}
        if isinstance(m, BiRecurrent):
            pf, _ = rebuild(m.fwd, params.get("fwd", {}), {})
            pb, _ = rebuild(m.bwd, params.get("bwd", {}), {})
            return {"fwd": pf, "bwd": pb}, state
        if isinstance(m, (QuantizedLinear, QuantizedSpatialConvolution)):
            return {}, {}
        return params, state

    q._params, q._state = rebuild(q, model._params, model._state)
    q._grads = jax.tree_util.tree_map(jnp.zeros_like, q._params)
    q.training = False
    return q
