"""Per-replica health state machine + per-model-version circuit breaker.

Reference: BigDL 2.0 Cluster Serving isolates failures per replica and
keeps routing around them (arXiv:2204.01715 §3.3); the same shape as
every production serving mesh: a replica's recent behavior decides how
much traffic it earns.

Replica state machine (``ReplicaHealth``)::

    HEALTHY ──failure×degraded_after──▶ DEGRADED
    DEGRADED ──failure×quarantine_after─▶ QUARANTINED
    DEGRADED ──success──▶ HEALTHY
    QUARANTINED ──probe ok──▶ HEALTHY        (re-admission)
    QUARANTINED ──probe fail─▶ QUARANTINED   (backoff doubles)

A quarantined replica receives **no** regular traffic; after a
probation delay (exponential backoff + deterministic seeded jitter so
re-admission storms from N replicas decorrelate *and* tests replay
exactly) it is offered exactly ONE live request as a probation probe —
success re-admits, failure doubles the backoff.  ``mark_dead`` jumps
straight to QUARANTINED (a dead batcher thread is not a statistics
question).

``CircuitBreaker`` is the model-*version* analog for the registry's
latest-wins routing: ``trip_after`` consecutive failures open the
breaker for ``cooldown_s`` (doubling on each re-trip, capped), during
which version resolution falls back to the previous deployed version —
a poisoned deploy stops eating traffic within ``trip_after`` requests
instead of burning the error budget until a human rolls back.  After
the cooldown the breaker is half-open: traffic flows again, the first
failure re-trips, a success closes it.

Everything here is host-side bookkeeping (no jax), same contract as
``telemetry/registry.py``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

import numpy as np

HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"

#: ``admit()`` verdicts
ADMIT = "admit"
PROBE = "probe"
REFUSE = "refuse"


@dataclasses.dataclass
class HealthPolicy:
    """Thresholds/backoff for one replica set (shared by its replicas)."""

    degraded_after: int = 1       # consecutive failures → DEGRADED
    quarantine_after: int = 3     # consecutive failures → QUARANTINED
    probe_backoff_s: float = 0.5  # first probation delay
    probe_backoff_factor: float = 2.0
    probe_backoff_max_s: float = 30.0
    probe_jitter: float = 0.25    # jitter as a fraction of the backoff
    seed: int = 0                 # jitter determinism


class ReplicaHealth:
    """Health ledger for ONE replica.  Thread-safe; ``clock`` is
    injectable so unit tests can drive probation without sleeping."""

    def __init__(self, ix: int, policy: Optional[HealthPolicy] = None,
                 registry=None, clock=time.monotonic, recorder=None):
        self.ix = ix
        self.policy = policy or HealthPolicy()
        self._registry = registry
        # optional telemetry.FlightRecorder: every state TRANSITION is
        # recorded there (events ride boundaries the machine already
        # crosses — no new work on the no-transition path)
        self._recorder = recorder
        self._clock = clock
        self._lock = threading.Lock()
        # the whole ledger mutates under one lock; `state` is exposed
        # as a lock-free read (stale by at most one transition)
        self._state = HEALTHY                # write-guarded-by: _lock
        self._consecutive_failures = 0       # guarded-by: _lock
        self._probes = 0                     # guarded-by: _lock
        self._probe_inflight = False         # guarded-by: _lock
        # guarded-by: _lock
        self._backoff_s = self.policy.probe_backoff_s
        self._next_probe_at = 0.0            # guarded-by: _lock

    # ------------------------------------------------------------ events
    def _count(self, name: str) -> None:
        if self._registry is not None:
            self._registry.counter(f"resilience/{name}").inc()

    def _transition(self, frm: str, to: str) -> None:
        if self._recorder is not None:
            self._recorder.record("health_transition", cat="resilience",
                                  replica=self.ix, frm=frm, to=to)

    # guarded-by: _lock
    def _quarantine_locked(self, now: float) -> None:
        if self._state != QUARANTINED:
            self._transition(self._state, QUARANTINED)
            self._state = QUARANTINED
            self._count("quarantines")
        self._schedule_probe_locked(now)

    # guarded-by: _lock
    def _schedule_probe_locked(self, now: float) -> None:
        p = self.policy
        # deterministic jitter: pure function of (seed, replica, probe#)
        jitter = float(np.random.default_rng(
            (p.seed, self.ix, self._probes)).random()) * p.probe_jitter
        self._next_probe_at = now + self._backoff_s * (1.0 + jitter)
        self._backoff_s = min(self._backoff_s * p.probe_backoff_factor,
                              p.probe_backoff_max_s)

    # -------------------------------------------------------------- api
    @property
    def state(self) -> str:
        return self._state

    def admit(self, now: Optional[float] = None) -> str:
        """Routing verdict for one request: ``ADMIT`` (regular traffic),
        ``PROBE`` (this request is the quarantined replica's one
        probation probe — the caller must report its outcome with
        ``probe=True``, or release the untried slot via
        :meth:`cancel_probe`; the PR-10 review-round-1 leak was
        exactly a consumed slot nobody released, which quarantined the
        replica forever) or ``REFUSE``.  The slot inc/dec sites are
        `# acquires:`/`# releases:`-tagged so GL303 keeps the pairing
        checkable in this file; the cross-file caller contract
        (``ReplicaSet._pick``/``_on_done``) stays prose — per-file
        models are the unit."""
        if now is None:
            now = self._clock()
        with self._lock:
            if self._state != QUARANTINED:
                return ADMIT
            if self._probe_inflight or now < self._next_probe_at:
                return REFUSE
            self._probe_inflight = True  # acquires: probe_slot
            self._probes += 1
            self._count("probes")
            return PROBE

    def cancel_probe(self) -> None:
        """Release an admitted probation probe WITHOUT recording an
        outcome — the probe never actually exercised the replica (the
        submit was refused by a full queue, or the request expired in
        line from pure congestion).  The probe window stays as
        scheduled, so the next due request simply probes instead."""
        with self._lock:
            self._probe_inflight = False  # releases: probe_slot

    def record_success(self, probe: bool = False) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if probe:
                self._probe_inflight = False  # releases: probe_slot
            if self._state == QUARANTINED:
                if not probe:
                    return  # stale non-probe completion; wait for probe
                self._transition(QUARANTINED, HEALTHY)
                self._state = HEALTHY
                self._backoff_s = self.policy.probe_backoff_s
                self._count("readmissions")
            elif self._state == DEGRADED:
                self._transition(DEGRADED, HEALTHY)
                self._state = HEALTHY

    def record_failure(self, probe: bool = False,
                       now: Optional[float] = None) -> None:
        if now is None:
            now = self._clock()
        with self._lock:
            self._consecutive_failures += 1
            if probe:
                self._probe_inflight = False  # releases: probe_slot
            if self._state == QUARANTINED:
                if probe:
                    # failed probation: stay out, schedule the next
                    # window (the doubled backoff applies there)
                    self._schedule_probe_locked(now)
                # a STALE non-probe failure (stranded requests from the
                # incident that quarantined us, draining in) must not
                # reschedule or double anything — one wedge with 8
                # requests in flight is one piece of evidence, not 8
                return
            p = self.policy
            if self._consecutive_failures >= p.quarantine_after:
                self._quarantine_locked(now)
            elif self._consecutive_failures >= p.degraded_after:
                if self._state != DEGRADED:
                    self._transition(self._state, DEGRADED)
                    self._state = DEGRADED
                    self._count("degradations")

    def mark_dead(self, now: Optional[float] = None) -> None:
        """Hard evidence (dead batcher thread): straight to QUARANTINED,
        no threshold arithmetic."""
        if now is None:
            now = self._clock()
        with self._lock:
            self._consecutive_failures = max(
                self._consecutive_failures,
                self.policy.quarantine_after)
            self._probe_inflight = False  # releases: probe_slot
            self._quarantine_locked(now)

    def next_probe_in(self, now: Optional[float] = None) -> float:
        """Seconds until the next probation probe (0 when not
        quarantined) — the load-shedding ``retry_after_ms`` hint when
        every replica is out."""
        if now is None:
            now = self._clock()
        with self._lock:
            if self._state != QUARANTINED:
                return 0.0
            return max(0.0, self._next_probe_at - now)

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive_failures,
                    "probes": self._probes,
                    "backoff_s": round(self._backoff_s, 3)}


class CircuitBreaker:
    """Consecutive-failure breaker for one deployed model version.

    ``allow()`` is the routing predicate: True while closed or once the
    cooldown has elapsed (half-open — traffic flows, the next failure
    re-trips with a doubled cooldown, a success closes and resets it).
    Overload rejections must NOT be recorded here — a full queue says
    nothing about whether the model itself is poisoned.
    """

    def __init__(self, trip_after: int = 5, cooldown_s: float = 30.0,
                 cooldown_factor: float = 2.0,
                 cooldown_max_s: float = 300.0, registry=None,
                 name: str = "", clock=time.monotonic, recorder=None):
        self.trip_after = max(1, int(trip_after))
        self._recorder = recorder  # optional telemetry.FlightRecorder
        self._base_cooldown_s = float(cooldown_s)
        self._cooldown_s = float(cooldown_s)  # guarded-by: _lock
        self._cooldown_factor = float(cooldown_factor)
        self._cooldown_max_s = float(cooldown_max_s)
        self._registry = registry
        self._name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive_failures = 0       # guarded-by: _lock
        # guarded-by: _lock
        self._opened_at: Optional[float] = None
        self.trips = 0                       # write-guarded-by: _lock

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._opened_at = None
            self._cooldown_s = self._base_cooldown_s

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            half_open = (self._opened_at is not None
                         and self._clock() >= self._opened_at
                         + self._cooldown_s)
            if half_open or (self._opened_at is None
                             and self._consecutive_failures
                             >= self.trip_after):
                if half_open:  # failed trial: back off harder
                    self._cooldown_s = min(
                        self._cooldown_s * self._cooldown_factor,
                        self._cooldown_max_s)
                self._opened_at = self._clock()
                self.trips += 1
                if self._registry is not None:
                    self._registry.counter(
                        "resilience/breaker_trips").inc()
                if self._recorder is not None:
                    self._recorder.record(
                        "breaker_trip", cat="resilience",
                        version=self._name, trips=self.trips,
                        cooldown_s=round(self._cooldown_s, 3))

    def allow(self, now: Optional[float] = None) -> bool:
        if now is None:
            now = self._clock()
        with self._lock:
            if self._opened_at is None:
                return True
            return now >= self._opened_at + self._cooldown_s  # half-open

    @property
    def open(self) -> bool:
        return not self.allow()

    def snapshot(self) -> dict:
        with self._lock:
            return {"open": (self._opened_at is not None
                             and self._clock() < self._opened_at
                             + self._cooldown_s),
                    "trips": self.trips,
                    "consecutive_failures": self._consecutive_failures,
                    "cooldown_s": round(self._cooldown_s, 3)}
