"""Deterministic, seeded fault injection.

Reference: the TensorFlow system paper's position that failures are
*expected events with designed-in recovery*, not exceptions
(arXiv:1605.08695 §4.4), and BigDL 2.0 Cluster Serving's per-replica
failure isolation (arXiv:2204.01715 §3.3).  A recovery path that is only
exercised by real outages is an untested path — this module makes every
degradation scenario in the stack reproducible on demand, so the
self-healing serving layer and the driver's numeric guard are gated by
tests instead of hand-checked during incidents.

Design rules (house style — the telemetry/checkpoint inertness
discipline applied to chaos):

- **Provably inert when off.**  ``FaultInjector.from_config()`` returns
  ``None`` for an empty ``Config.fault_plan`` — every call site guards
  on ``injector is not None``, so the disabled path executes byte-
  identical code (bitwise loss sequences, unchanged dispatch counts,
  serving outputs bitwise-equal to direct ``model.apply``; gated in
  ``tests/test_resilience.py``).
- **Deterministic given (plan, seed).**  Probabilistic clauses draw from
  ``np.random.default_rng((seed, clause_ix, index))`` — a pure function
  of the event index, never of wall clock or arrival order, so a flaky
  repro can be replayed exactly.
- **Scoped.**  Every clause can be pinned to an event index window
  (``at``/``after``/``until``/``every``), a firing budget (``count``), a
  replica (``target``) and a probability (``p``).

Plan grammar (``Config.fault_plan`` / ``BIGDL_TPU_FAULT_PLAN``)::

    plan   := clause (";" clause)*
    clause := kind ["@" key "=" val ("," key "=" val)*]
    kind   := dispatch_error    -- raise InjectedFault at a dispatch
            | dispatch_delay    -- sleep ms= before a dispatch (straggler)
            | replica_death     -- kill the serving replica's batcher
                                   thread (a BaseException escapes the
                                   dispatch error handler, exactly like
                                   a real thread crash)
            | corrupt_batch     -- NaN-poison the staged training batch
            | nonfinite_grads   -- Inf-poison the staged training batch
                                   (overflows forward/backward)
            | resize            -- open a graceful membership epoch
                                   shrinking/regrowing the world to to=
            | host_loss         -- preemption warning: graceful shrink
                                   (default to= half the world)
            | device_loss       -- abrupt device loss: shrink with the
                                   in-flight block abandoned
                                   (default to= world - 1)
    keys   := at | after | until | every | count | target | p | ms | to
            | where (serving|driver — dispatch_* kinds only;
                     default serving)

Event indices: serving clauses fire on a replica's own dispatch counter;
driver ``dispatch_*@where=driver`` clauses fire on the driver's dispatch
counter; batch kinds AND membership kinds fire on the global iteration
number (so ``corrupt_batch@at=7`` poisons exactly iteration 7's
microbatch, and ``resize@at=7,to=2`` opens the shrink epoch the moment
step 7 is replayed).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

import numpy as np


class InjectedFault(RuntimeError):
    """A fault raised on purpose by the injector (transient by
    construction — retry/failover paths treat it like any dispatch
    error)."""


class ReplicaDeathFault(BaseException):
    """Kills the batcher thread it is raised on.  Deliberately NOT an
    ``Exception``: the serving dispatch wrapper resolves futures for any
    ``Exception``, and a replica death must instead strand them exactly
    the way a real thread crash does (the failure mode ``ReplicaSet``'s
    supervisor exists to detect)."""


_SERVING_KINDS = ("dispatch_error", "dispatch_delay", "replica_death")
_BATCH_KINDS = ("corrupt_batch", "nonfinite_grads")
_MEMBERSHIP_KINDS = ("resize", "host_loss", "device_loss")
KINDS = _SERVING_KINDS + _BATCH_KINDS + _MEMBERSHIP_KINDS

_INT_KEYS = ("at", "after", "until", "every", "count", "target", "to")
_FLOAT_KEYS = ("p", "ms")
_STR_KEYS = ("where",)


class FaultClause:
    """One parsed clause.  ``fired`` is the mutable firing budget
    counter — host-side state, serialized by the injector lock."""

    __slots__ = ("kind", "at", "after", "until", "every", "count",
                 "target", "p", "ms", "to", "where", "fired")

    def __init__(self, kind: str, **keys):
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; kinds: {KINDS}")
        self.kind = kind
        self.at = keys.pop("at", None)
        self.after = keys.pop("after", None)
        self.until = keys.pop("until", None)
        self.every = keys.pop("every", None)
        self.count = keys.pop("count", None)
        self.target = keys.pop("target", None)
        self.p = float(keys.pop("p", 1.0))
        self.ms = float(keys.pop("ms", 10.0))
        self.to = keys.pop("to", None)
        self.where = keys.pop("where", "serving")
        self.fired = 0
        if keys:
            raise ValueError(
                f"unknown fault key(s) {sorted(keys)} for {kind!r}; "
                f"keys: {_INT_KEYS + _FLOAT_KEYS + _STR_KEYS}")
        if self.where not in ("serving", "driver"):
            raise ValueError(
                f"where= must be serving|driver, got {self.where!r}")
        if kind in _BATCH_KINDS + _MEMBERSHIP_KINDS \
                and self.where == "serving":
            # batch and membership kinds only exist in the driver
            self.where = "driver"
        if self.to is not None and kind not in _MEMBERSHIP_KINDS:
            raise ValueError(
                f"to= only applies to membership kinds "
                f"{_MEMBERSHIP_KINDS}, not {kind!r}")
        if kind == "resize" and (self.to is None or self.to < 1):
            raise ValueError(
                "resize needs an explicit target world: to=<n> >= 1")
        if kind in _MEMBERSHIP_KINDS and self.count is None:
            # one membership event per clause unless asked otherwise:
            # an elastic restore REWINDS the step counter, and a
            # budget-less at= clause would re-fire on every replay
            # crossing (a default-to device_loss would then shrink the
            # roster again each pass — a runaway)
            self.count = 1
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"p= must be in [0, 1], got {self.p}")
        if self.every is not None and self.every < 1:
            raise ValueError(f"every= must be >= 1, got {self.every}")

    def matches(self, index: int, replica: Optional[int]) -> bool:
        """Window/target predicate — pure function of (index, replica),
        no side effects (the firing-budget check lives in the injector
        under its lock)."""
        if self.target is not None and replica != self.target:
            return False
        if self.at is not None and index != self.at:
            return False
        if self.after is not None and index < self.after:
            return False
        if self.until is not None and index >= self.until:
            return False
        if self.every is not None and index % self.every != 0:
            return False
        return True

    def describe(self) -> str:
        keys = []
        for k in _INT_KEYS + _FLOAT_KEYS + _STR_KEYS:
            v = getattr(self, k)
            if v is not None and not (k == "p" and v == 1.0) \
                    and not (k == "ms" and v == 10.0) \
                    and not (k == "where" and v == "serving"):
                keys.append(f"{k}={v}")
        return self.kind + ("@" + ",".join(keys) if keys else "")


def parse_fault_plan(plan: str) -> List[FaultClause]:
    """Parse the plan grammar (module docstring).  Loud on anything
    unknown — a typo'd chaos plan that silently injects nothing would
    report a recovery path as tested when it never ran."""
    clauses: List[FaultClause] = []
    for raw in (plan or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        kind, _, argstr = raw.partition("@")
        kind = kind.strip()
        keys = {}
        if argstr:
            for tok in argstr.split(","):
                k, eq, v = tok.partition("=")
                k = k.strip()
                if not eq:
                    raise ValueError(
                        f"fault clause {raw!r}: expected key=value, "
                        f"got {tok!r}")
                if k in _INT_KEYS:
                    keys[k] = int(v)
                elif k in _FLOAT_KEYS:
                    keys[k] = float(v)
                elif k in _STR_KEYS:
                    keys[k] = v.strip()
                else:
                    raise ValueError(
                        f"fault clause {raw!r}: unknown key {k!r}; "
                        f"keys: {_INT_KEYS + _FLOAT_KEYS + _STR_KEYS}")
        clauses.append(FaultClause(kind, **keys))
    return clauses


class FaultInjector:
    """Evaluates a parsed fault plan at instrumented sites.

    One injector may be shared by many threads (every serving replica's
    batcher polls it); the firing-budget bookkeeping is behind one lock.
    Injected events are counted into the attached
    :class:`~bigdl_tpu.telemetry.registry.MetricRegistry` as
    ``resilience/fault_<kind>`` counters so a chaos run's injected load
    is auditable next to the recovery metrics it provoked.
    """

    def __init__(self, plan: str, seed: int = 0, registry=None):
        self.plan = plan
        self.seed = int(seed)
        self.clauses = parse_fault_plan(plan)
        self._lock = threading.Lock()
        self._registry = registry

    @classmethod
    def from_config(cls, registry=None) -> Optional["FaultInjector"]:
        """``None`` (the provably-inert state) unless ``Config.
        fault_plan`` / ``BIGDL_TPU_FAULT_PLAN`` names a plan."""
        from bigdl_tpu.utils.config import get_config
        cfg = get_config()
        if not cfg.fault_plan:
            return None
        return cls(cfg.fault_plan, seed=cfg.fault_seed, registry=registry)

    def attach_registry(self, registry) -> None:
        self._registry = registry

    # ----------------------------------------------------------- firing
    def _fires(self, clause_ix: int, clause: FaultClause, index: int,
               replica: Optional[int]) -> bool:
        if not clause.matches(index, replica):
            return False
        if clause.p < 1.0:
            # deterministic: a pure function of (seed, clause, index) —
            # replayable regardless of thread interleaving
            r = np.random.default_rng(
                (self.seed, clause_ix, index)).random()
            if r >= clause.p:
                return False
        with self._lock:
            if clause.count is not None and clause.fired >= clause.count:
                return False
            clause.fired += 1
        if self._registry is not None:
            self._registry.counter(
                f"resilience/fault_{clause.kind}").inc()
        return True

    def _firing(self, kinds: Sequence[str], where: str, index: int,
                replica: Optional[int] = None) -> List[FaultClause]:
        return [c for ix, c in enumerate(self.clauses)
                if c.kind in kinds and c.where == where
                and self._fires(ix, c, index, replica)]

    # ------------------------------------------------------------ sites
    def serving_dispatch(self, index: int,
                         replica: Optional[int] = None) -> None:
        """Site: a serving replica's dispatch, keyed by that replica's
        own dispatch counter.  Delays apply first (a straggler can also
        die), then errors, then death."""
        fired = self._firing(_SERVING_KINDS, "serving", index, replica)
        for c in fired:
            if c.kind == "dispatch_delay":
                time.sleep(c.ms / 1e3)
        for c in fired:
            if c.kind == "dispatch_error":
                raise InjectedFault(
                    f"injected serving dispatch error "
                    f"(replica={replica}, dispatch={index})")
        for c in fired:
            if c.kind == "replica_death":
                raise ReplicaDeathFault(
                    f"injected replica death (replica={replica}, "
                    f"dispatch={index})")

    def driver_dispatch(self, index: int) -> None:
        """Site: the training driver's jit dispatch, keyed by the
        driver's dispatch counter (``dispatch_*@where=driver``)."""
        fired = self._firing(("dispatch_error", "dispatch_delay"),
                             "driver", index)
        for c in fired:
            if c.kind == "dispatch_delay":
                time.sleep(c.ms / 1e3)
        for c in fired:
            if c.kind == "dispatch_error":
                raise InjectedFault(
                    f"injected driver dispatch error (dispatch={index})")

    def batch_kinds(self, step: int) -> List[str]:
        """Site: one staged training microbatch, keyed by its global
        iteration number.  Returns the poison kinds firing at ``step``."""
        return [c.kind
                for c in self._firing(_BATCH_KINDS, "driver", step)]

    def has_membership_kinds(self) -> bool:
        """Whether the plan contains any ``resize``/``host_loss``/
        ``device_loss`` clause — the driver arms a
        :class:`~bigdl_tpu.resilience.membership.ClusterMembership`
        only then (plan without them stays membership-free)."""
        return any(c.kind in _MEMBERSHIP_KINDS for c in self.clauses)

    def membership_events(self, step: int) -> List[FaultClause]:
        """Site: the driver's replayed iteration, keyed by the global
        iteration number.  Returns the membership clauses firing at
        ``step`` (the driver translates them into
        ``ClusterMembership`` signals — this module stays free of any
        roster knowledge)."""
        return self._firing(_MEMBERSHIP_KINDS, "driver", step)

    def corrupt_staged(self, xs, first_step: int, k: int):
        """Poison the float leaves of a staged K-step block for every
        step whose batch-kind clause fires (``corrupt_batch`` → NaN,
        ``nonfinite_grads`` → Inf).  Runs eagerly on the already-placed
        block — only ever reached when a plan is live, so the off path
        stays byte-identical."""
        import jax
        import jax.numpy as jnp
        for j in range(k):
            kinds = self.batch_kinds(first_step + j)
            if not kinds:
                continue
            bad = float("nan") if "corrupt_batch" in kinds else float("inf")

            def poison(a, _j=j, _bad=bad):
                a = jnp.asarray(a)
                if not jnp.issubdtype(a.dtype, jnp.inexact):
                    return a
                return a.at[_j].set(_bad)

            xs = jax.tree_util.tree_map(poison, xs)
        return xs

    def describe(self) -> str:
        return "; ".join(c.describe() for c in self.clauses)
