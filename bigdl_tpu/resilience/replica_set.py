"""ReplicaSet — self-healing replica-per-device serving.

ROADMAP serving item 1a + the resilience layer: one
:class:`~bigdl_tpu.serving.InferenceService` (own bounded queue, own
batcher thread, own AOT bucket executables) per device, fronted by a
router that makes replica failure a routing event instead of an outage
(reference: BigDL 2.0 Cluster Serving's per-replica failure isolation
and backpressure, arXiv:2204.01715 §3.3).

Contract:

- **Least-queue-depth dispatch.**  Each request goes to the admitted
  replica with the shallowest queue (ties break on the lowest index —
  deterministic).  On an 8-chip host this is the 8× fan-out of one
  ``ModelRegistry`` entry; on a CPU host N replicas emulate the topology
  on one device (how the tier-1 tests and ``bench.py --resilience``
  exercise every path below).
- **Per-request deadlines, propagated.**  ``deadline_ms`` stamps each
  request with a monotonic deadline that travels WITH it through the
  replica's queue (``serving/batcher._Request.deadline``): the batcher
  refuses to dispatch expired work, and the supervisor fails requests
  stuck on a wedged/dead replica so the router can move them.
- **Bounded retry — inference is idempotent.**  A failed or timed-out
  request is retried on a different healthy replica up to
  ``max_retries`` times while its deadline allows.  An accepted request
  is therefore never silently dropped: it resolves with a result or an
  explicit error (gated in ``tests/test_resilience.py`` and the
  subprocess kill test).
- **Health state machine per replica** (``resilience/health.py``):
  failures degrade → quarantine; a quarantined replica gets zero
  traffic until its probation probe (exponential backoff + seeded
  jitter) succeeds.  A replica whose batcher thread DIED is detected by
  the supervisor (liveness poll — the one place in the serving stack
  that polls, because a dead thread cannot notify), quarantined
  immediately, its stranded requests failed over, and its batcher
  **revived** (fresh thread over the same warmed executables —
  ``InferenceService.revive``) so probation has something to probe.
- **Queue-pressure load shedding.**  When no admitted replica can take
  the request (all queues full, or everything quarantined), the set
  sheds with :class:`~bigdl_tpu.serving.ServiceOverloaded` carrying a
  ``retry_after_ms`` hint (queue drain rate when queues are the
  problem, next probation window when health is).

All events flow into one :class:`~bigdl_tpu.telemetry.registry.
MetricRegistry` (``resilience/*`` counters) and, when given, a tracer
(instant events per quarantine/readmission/failover).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, List, Optional, Sequence

from bigdl_tpu.resilience.faults import FaultInjector
from bigdl_tpu.resilience.health import (PROBE, QUARANTINED,
                                         HealthPolicy, ReplicaHealth)
from bigdl_tpu.serving.batcher import (DeadlineExceeded, ServiceClosed,
                                       ServiceOverloaded,
                                       settle_future as _settle)
from bigdl_tpu.serving.service import InferenceService
from bigdl_tpu.telemetry.registry import MetricRegistry

logger = logging.getLogger("bigdl_tpu.resilience")


class ReplicaDeadError(RuntimeError):
    """The replica holding this request died (batcher thread gone) —
    the supervisor resolves the stranded future with this so the router
    can fail over."""


class _Route:
    """Caller-facing request state: the outer future plus the retry
    budget.  One _Route may span several replica attempts;
    ``last_exc`` remembers the most recent attempt's real failure so
    running out of replicas surfaces THAT, not a fabricated shed.
    ``ctx`` (optional RequestContext) accumulates the hop history —
    one entry per attempt, outcome stamped at completion."""

    __slots__ = ("x", "outer", "deadline", "tries_left", "tried",
                 "last_exc", "ctx")

    def __init__(self, x, outer: Future, deadline: Optional[float],
                 tries_left: int, ctx=None):
        self.x = x
        self.outer = outer
        self.deadline = deadline
        self.tries_left = tries_left
        self.tried: set = set()
        self.last_exc: Optional[BaseException] = None
        self.ctx = ctx


class ReplicaSet:
    """N replicas of one model behind least-queue-depth routing with
    health tracking, failover and load shedding.  See module docstring.

    Parameters beyond the :class:`InferenceService` knobs:

    - ``n_replicas``: replica count; default one per local device.
      More replicas than devices is legal (emulated replicas — they
      round-robin over ``devices``).
    - ``devices``: placement targets; default ``jax.local_devices()``.
      Each replica's params/state are ``device_put`` onto its device so
      its dispatches run there (replica-per-chip routing).
    - ``deadline_ms``: per-request deadline (default
      ``Config.serving_deadline_ms``; 0 = none).
    - ``max_retries``: failover budget per request (attempts = 1 +
      max_retries).
    - ``health``: a :class:`HealthPolicy` (thresholds/probation
      backoff) shared by all replicas.
    - ``registry`` / ``tracer``: where resilience events land.  With
      ``Config.request_tracing`` on and no tracer given, the set mints
      its own so request spans/flow edges have somewhere to go.
    - ``flight``: optional :class:`~bigdl_tpu.telemetry.FlightRecorder`
      (None = ``telemetry.flight.from_config()``, which is None — the
      inert state — unless ``Config.flight_recorder_path`` is set).
      Deaths, quarantines, failovers, sheds, probes and revivals are
      recorded there with the victim request's trace_id, so a crash
      dump tells the full story (``tools/obs_report.py``).
    - ``request_tracing``: mint a :class:`~bigdl_tpu.telemetry.
      RequestContext` per submit (None = ``Config.request_tracing``);
      contexts carry the per-request hop history.
    - ``priority_fn``: QoS preemption hook handed to every replica's
      batcher (see :class:`InferenceService`); the frontend's
      :class:`~bigdl_tpu.frontend.QosAdmission` supplies it so
      latency-class tenants preempt batch backlog per replica queue.

    **Elastic replica count** (``set_replica_count``): replicas live in
    index-stable SLOTS.  Growing warms a new replica OFF the routing
    path (AOT bucket compiles finish before the slot is admitted);
    shrinking retires the highest active slot through the quarantine
    discipline — the retired slot gets zero new traffic while its
    accepted backlog drains to completion, then its executables and
    params are released.  Retired slots keep their index (in-flight
    bookkeeping, health ledgers and fault targeting stay stable) and
    are reused by the next grow.
    """

    _SUPERVISOR_POLL_S = 0.02  # liveness/deadline sweep while inflight

    def __init__(self, model, params=None, state=None, *,
                 n_replicas: Optional[int] = None,
                 devices: Optional[Sequence] = None,
                 input_spec=None, max_batch_size: Optional[int] = None,
                 batch_timeout_ms: Optional[float] = None,
                 queue_capacity: Optional[int] = None, buckets=None,
                 workload: Optional[str] = None, name: str = "model",
                 deadline_ms: Optional[float] = None,
                 max_retries: int = 2,
                 health: Optional[HealthPolicy] = None,
                 fault_injector: Optional[FaultInjector] = None,
                 registry: Optional[MetricRegistry] = None,
                 tracer=None, start: bool = True, flight=None,
                 request_tracing: Optional[bool] = None,
                 priority_fn=None):
        import jax

        from bigdl_tpu.telemetry import admin as _admin
        from bigdl_tpu.telemetry import flight as _flight_mod
        from bigdl_tpu.utils.config import get_config

        self.name = name
        self.registry = registry if registry is not None \
            else MetricRegistry()
        if request_tracing is None:
            request_tracing = get_config().request_tracing
        self._request_tracing = bool(request_tracing)
        if tracer is None and self._request_tracing:
            from bigdl_tpu.telemetry.tracer import Tracer
            tracer = Tracer(enabled=True)
        self.tracer = tracer
        self._flight = flight if flight is not None \
            else _flight_mod.from_config()
        self.max_retries = max(0, int(max_retries))
        if deadline_ms is None:
            # the same explicit > env > tuned[workload] > default chain
            # the other serving knobs resolve through
            from bigdl_tpu.engine import Engine
            deadline_ms = Engine.serving_defaults(workload)["deadline_ms"]
        self.deadline_s = (float(deadline_ms) / 1e3
                           if deadline_ms and deadline_ms > 0 else None)
        if fault_injector is None:
            fault_injector = FaultInjector.from_config(
                registry=self.registry)
        else:
            fault_injector.attach_registry(self.registry)
        self._faults = fault_injector

        if devices is None:
            devices = jax.local_devices()
        if n_replicas is None:
            n_replicas = len(devices)
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1: {n_replicas}")
        if params is None:
            model._ensure_init()
            params, state = model._params, model._state
        state = state if state is not None else {}

        # construction materials retained for set_replica_count grow:
        # a later replica must be built EXACTLY like the originals
        # (same params source, same devices round-robin, same policy)
        self._model = model
        self._base_params = params
        self._base_state = state
        self._devices = list(devices)
        self._policy = policy = health or HealthPolicy()
        self._input_spec = input_spec
        self._workload = workload
        self._started = bool(start)
        self._priority_fn = priority_fn
        self._service_kw = dict(
            max_batch_size=max_batch_size,
            batch_timeout_ms=batch_timeout_ms,
            queue_capacity=queue_capacity, buckets=buckets)
        self._replicas: List[InferenceService] = []
        self._health: List[ReplicaHealth] = []
        for i in range(int(n_replicas)):
            svc, h = self._build_replica(i, input_spec)
            self._replicas.append(svc)
            self._health.append(h)
            if i == 0:
                # freeze the RESOLVED knobs off replica 0 so replicas
                # grown later match the originals even if config/env
                # defaults drift between now and then
                self._service_kw = dict(
                    max_batch_size=svc.max_batch_size,
                    batch_timeout_ms=svc.batch_timeout_ms,
                    queue_capacity=svc.queue_capacity,
                    buckets=svc.buckets)

        # counters created eagerly so a zero-event run still snapshots
        # the full schema
        for c in ("failovers", "sheds", "quarantines",
                  "readmissions", "probes", "degradations",
                  "deadline_timeouts", "replica_deaths", "revivals",
                  "replicas_added", "replicas_retired"):
            self.registry.counter(f"resilience/{c}")

        # admin plane: config-driven start + source registration — the
        # set-level resilience counters, every replica's serving
        # registry, the tracer, and a health provider all scrape from
        # one endpoint (admin_port=0 → None: nothing runs).  The name
        # is minted unique so two same-named sets don't evict each
        # other; replicas minted their own unique names above.
        self._admin_name: Optional[str] = None
        _srv = _admin.maybe_start()
        if _srv is not None:
            self._admin_name = _srv.unique_source_name(self.name)
            _srv.add_registry(self._admin_name, self.registry)
            _srv.add_health(self._admin_name, self.health_snapshot)
            if self.tracer is not None:
                _srv.add_tracer(self._admin_name, self.tracer)
            if self._flight is not None:
                _srv.set_flight(self._flight)

        self._lock = threading.Lock()
        # one death handler may run per replica at a time: routing and
        # the supervisor can both spot the same dead batcher, and a
        # double-revive would double-count the death in the metrics
        self._death_locks = [threading.Lock()
                             for _ in range(len(self._replicas))]
        # retired slots (orderly scale-down, NOT deaths): excluded from
        # routing and from the supervisor's death detection while their
        # backlog drains.  Replaced wholesale (copy-on-write frozenset)
        # so the lock-free readers on the routing path always see a
        # consistent set; write-guarded-by: _lock
        self._retired: frozenset = frozenset()
        # serializes set_replica_count operations (autoscaler vs manual
        # scaling); NEVER taken on a request path
        self._scale_lock = threading.Lock()
        # token -> (route, ix, inner, probe); guarded-by: _lock
        self._inflight: dict = {}
        self._token = itertools.count()
        # lifecycle flag/thread: written under the lock, read lock-free
        # on fast paths (submit's early refusal, stop's join)
        self._stopped = False  # write-guarded-by: _lock
        # write-guarded-by: _lock
        self._supervisor: Optional[threading.Thread] = None
        self._wake = threading.Condition(self._lock)

    # ---------------------------------------------------- replica build
    def _build_replica(self, ix: int, input_spec):
        """Construct replica ``ix``: params/state committed onto device
        ``ix % D`` (the replica's jit follows its params' device, so
        its dispatches run on that chip — the replica-per-chip routing
        of ROADMAP 1a) behind a fresh :class:`InferenceService` and a
        fresh health ledger.  With an ``input_spec`` the AOT bucket
        warmup happens HERE, before the caller admits the slot to
        routing — a grown replica never serves a compile stall."""
        import jax
        dev = self._devices[ix % len(self._devices)]
        p_i = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, dev), self._base_params)
        s_i = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, dev), self._base_state)
        svc = InferenceService(
            self._model, p_i, s_i, input_spec=input_spec,
            workload=self._workload, name=f"{self.name}/r{ix}",
            start=self._started, fault_injector=self._faults,
            tracer=self.tracer,
            request_tracing=self._request_tracing,
            priority_fn=self._priority_fn, **self._service_kw)
        svc._fault_replica = ix
        health = ReplicaHealth(ix, policy=self._policy,
                               registry=self.registry,
                               recorder=self._flight)
        return svc, health

    # ------------------------------------------------------------ events
    def _instant(self, event: str, **args) -> None:
        if self.tracer is not None:
            self.tracer.instant(event, cat="resilience", **args)

    def _flight_event(self, event: str, trace_id=None, **fields) -> None:
        if self._flight is not None:
            self._flight.record(event, cat="resilience",
                                trace_id=trace_id, model=self.name,
                                **fields)

    # ----------------------------------------------------------- routing
    def _pick(self, route: _Route):
        """(replica_ix, probe?) of the admitted replica with the
        shallowest queue, or None.  Dead replicas found here are
        quarantined + revived on the spot (routing-time liveness — the
        supervisor only watches replicas with inflight work).

        ``admit()`` on a quarantined replica CONSUMES its one probation
        probe slot, so it may only be asked once a replica is actually
        selected — asking every candidate and dispatching one would
        leak ``_probe_inflight`` on the rest and quarantine them
        forever.  Hence two passes: quarantined replicas first (a due
        probe is preferred — re-admission must make progress under
        sustained load; at most ONE admit() call, on the selected
        replica), then least-queue-depth over the healthy rest."""
        now = time.monotonic()
        eligible = []
        for i, svc in enumerate(self._replicas):
            if i in route.tried:
                continue
            if not svc.alive:
                # alive read BEFORE the retired check: retirement marks
                # the slot retired first, THEN stops the service, so a
                # reader seeing alive=False is guaranteed a current
                # retired verdict (an orderly drain is not a death)
                if i not in self._retired:
                    self._on_replica_dead(i)
                continue
            if i in self._retired:
                continue  # retiring: backlog drains, no new routes
            eligible.append((i, svc))
        for i, svc in eligible:
            if self._health[i].state == QUARANTINED:
                if self._health[i].admit(now) == PROBE:
                    return i, True
        candidates = [(svc.queue_depth(), i) for i, svc in eligible
                      if self._health[i].state != QUARANTINED]
        if not candidates:
            return None
        candidates.sort()
        return candidates[0][1], False

    def _shed(self, route: _Route, initial: bool,
              last_overload: Optional[ServiceOverloaded]) -> None:
        """No admissible replica: shed with a retry-after hint — the
        queue drain estimate when queues are the problem, the next
        probation window when health is."""
        self.registry.counter("resilience/sheds").inc()
        self._instant("shed", model=self.name)
        self._flight_event("shed", trace_id=(route.ctx.trace_id
                                             if route.ctx is not None
                                             else None))
        if last_overload is not None:
            retry_ms = last_overload.retry_after_ms
            depth, cap = last_overload.queue_depth, last_overload.capacity
        else:
            waits = [h.next_probe_in() for h in self._health
                     if h.state == "quarantined"]
            retry_ms = round(min(waits) * 1e3, 1) if waits else None
            depth = sum(s.queue_depth() for s in self._replicas)
            cap = sum(s.queue_capacity for s in self._replicas)
        exc = ServiceOverloaded(depth, cap, self.name,
                                retry_after_ms=retry_ms)
        if initial:
            raise exc
        _settle(route.outer, exc=exc)

    def _attempt(self, route: _Route, initial: bool = False) -> None:
        """Submit one attempt.  Runs on the caller thread (initial) or a
        replica batcher/supervisor thread (failover) — everything here
        is lock-cheap, no device work."""
        last_overload: Optional[ServiceOverloaded] = None
        while True:
            if route.outer.done():
                return  # caller cancelled / already settled
            picked = self._pick(route)
            if picked is None:
                if route.last_exc is not None:
                    # every replica was tried and the last one FAILED —
                    # that failure is the diagnosis, not overload: a
                    # deterministic model bug reported as a shed would
                    # send callers into a futile retry-after loop
                    _settle(route.outer, exc=route.last_exc)
                    return
                self._shed(route, initial, last_overload)
                return
            ix, probe = picked
            svc = self._replicas[ix]
            try:
                inner = svc.submit(route.x, deadline=route.deadline,
                                   ctx=route.ctx)
            except ServiceOverloaded as e:
                last_overload = e
                if probe:
                    # the probe never ran — release its slot without an
                    # outcome so the replica stays probe-able
                    self._health[ix].cancel_probe()
                route.tried.add(ix)  # full queue: look elsewhere (not a
                continue             # health failure)
            except ServiceClosed:
                if probe:
                    self._health[ix].cancel_probe()
                self._on_replica_dead(ix)
                route.tried.add(ix)
                continue
            except Exception as e:  # malformed request et al: caller bug
                if probe:
                    # the replica never saw the request — release the
                    # probe without an outcome (a caller bug must not
                    # extend someone else's quarantine)
                    self._health[ix].cancel_probe()
                if initial:
                    raise
                _settle(route.outer, exc=e)
                return
            if route.ctx is not None:
                # the request's hop history: one entry per accepted
                # attempt, outcome stamped in _on_done — a failed-over
                # request reads "r0: ReplicaDeadError → r2: ok".  The
                # flight recorder only sees the RARE path: retry
                # landings (attempt > 1).  First attempts are routine
                # traffic — recording them would put a locked
                # write+flush on every request and evict the rare
                # death/quarantine events from the bounded ring; the
                # original dispatch's replica still reaches the dump
                # on the failover event's hops field.
                route.ctx.add_hop(ix, probe=probe)
                if len(route.ctx.hops) > 1:
                    self._flight_event("request_route",
                                       trace_id=route.ctx.trace_id,
                                       replica=ix, probe=probe,
                                       attempt=len(route.ctx.hops))
            token = next(self._token)
            with self._lock:
                # every entry stored here is popped by exactly one
                # _on_done (late completion, supervisor timeout and
                # stranded-sweep all settle `inner`, which fires the
                # done callback) — the GL303-tracked pairing
                self._inflight[token] = (route, ix, inner, probe)  # acquires: rs_inflight
                self._ensure_supervisor_locked()
                self._wake.notify_all()
            inner.add_done_callback(
                lambda _f, _t=token: self._on_done(_t))
            return

    # -------------------------------------------------------- completion
    def _on_done(self, token) -> None:
        with self._lock:
            entry = self._inflight.pop(token, None)  # releases: rs_inflight
        if entry is None:
            return
        route, ix, inner, probe = entry
        health = self._health[ix]
        if inner.cancelled():
            exc: Optional[BaseException] = ServiceClosed(
                f"replica {ix} cancelled the request")
        else:
            exc = inner.exception()
        if route.ctx is not None and route.ctx.hops:
            # hops are appended one at a time and at most one attempt
            # of a route is in flight, so the last hop is this one
            route.ctx.hops[-1]["outcome"] = (
                "ok" if exc is None else type(exc).__name__)
        if exc is None:
            health.record_success(probe=probe)
            if probe:
                self._instant("readmission_probe_ok", replica=ix)
                self._flight_event("readmission_probe_ok", replica=ix)
            _settle(route.outer, result=inner.result())
            return
        # failure: classify, record, maybe fail over
        if isinstance(exc, ReplicaDeadError):
            pass  # _on_replica_dead already recorded it
        elif isinstance(exc, DeadlineExceeded):
            self.registry.counter("resilience/deadline_timeouts").inc()
            if getattr(exc, "wedged", False):
                # the SUPERVISOR resolved it: the batcher missed its
                # own deadline window — evidence against the replica
                health.record_failure(probe=probe)
            elif probe:
                # the batcher itself refused expired work: the replica
                # is alive and draining, the queue was just long —
                # congestion is not a poison signal (the breaker
                # contract, applied to replica health: a deadline storm
                # under pure overload must not cascade-quarantine the
                # set).  Release the probe without an outcome.
                health.cancel_probe()
        else:
            health.record_failure(probe=probe)
        if probe:
            self._instant("readmission_probe_failed", replica=ix)
        now = time.monotonic()
        out_of_time = route.deadline is not None and now >= route.deadline
        if route.tries_left > 0 and not out_of_time \
                and not route.outer.done():
            route.tries_left -= 1
            route.tried.add(ix)
            route.last_exc = exc  # surfaced if no replica is left
            self.registry.counter("resilience/failovers").inc()
            trace_id = route.ctx.trace_id if route.ctx is not None \
                else None
            self._instant("failover", replica=ix,
                          error=type(exc).__name__,
                          **({"trace_id": trace_id} if trace_id else {}))
            # the hop history rides the failover event, so the dump
            # shows the ORIGINAL dispatch replica without a per-request
            # route event (see _attempt)
            hops = ([f"r{h['replica']}:{h['outcome']}"
                     for h in route.ctx.hops]
                    if route.ctx is not None else None)
            self._flight_event("failover", trace_id=trace_id,
                               replica=ix, error=type(exc).__name__,
                               **({"hops": hops} if hops else {}))
            self._attempt(route)
            return
        _settle(route.outer, exc=exc)

    # -------------------------------------------------------- supervisor
    # guarded-by: _lock
    def _ensure_supervisor_locked(self) -> None:
        if self._supervisor is None or not self._supervisor.is_alive():
            self._supervisor = threading.Thread(
                target=self._supervise, name=f"{self.name}-supervisor",
                daemon=True)
            self._supervisor.start()

    def _supervise(self) -> None:
        """Liveness + stuck-request sweep.  The batcher itself honors
        deadlines for work it actually dispatches; this loop exists for
        the work a batcher can no longer dispatch — dead thread, wedged
        straggler — where only an outside observer can resolve the
        future.  Polling is unavoidable here (a dead thread cannot
        notify); the poll only runs while requests are in flight."""
        grace = self._SUPERVISOR_POLL_S
        while True:
            with self._lock:
                if self._stopped:
                    return
                if not self._inflight:
                    self._wake.wait(timeout=1.0)
                    continue
                entries = list(self._inflight.items())
            now = time.monotonic()
            dead = set()
            for token, (route, ix, inner, probe) in entries:
                if inner.done():
                    continue
                if not self._replicas[ix].alive:
                    if ix in self._retired:
                        # orderly retirement mid-drain (alive read
                        # before retired — see _pick): the stop() in
                        # _retire_replica resolves this backlog, and
                        # sweeps any remainder itself on timeout
                        continue
                    dead.add(ix)
                    _settle(inner, exc=ReplicaDeadError(
                        f"replica {ix} of {self.name!r} died with this "
                        f"request in flight"))
                elif route.deadline is not None \
                        and now >= route.deadline + grace:
                    # expired without the batcher resolving it: settle
                    # from outside.  Tagged `wedged` — evidence against
                    # the replica — ONLY when the batcher has made no
                    # dispatch progress since the deadline passed; a
                    # batcher that is actively draining just has a
                    # queue longer than the deadline (congestion, not
                    # poison — it will refuse this request itself soon,
                    # and under a pure overload storm the supervisor
                    # must not cascade-quarantine healthy replicas)
                    progress = self._replicas[ix].last_progress
                    exc = DeadlineExceeded(
                        f"request deadline exceeded on replica {ix}")
                    exc.wedged = (progress is None
                                  or progress < route.deadline)
                    _settle(inner, exc=exc)
            for ix in dead:
                self._on_replica_dead(ix)
            with self._lock:
                if self._stopped:
                    return
                self._wake.wait(timeout=self._SUPERVISOR_POLL_S)

    def _on_replica_dead(self, ix: int) -> None:
        """Quarantine + revive a replica whose batcher thread died, and
        fail over the requests stranded ON it.  Idempotent per death:
        revive() is a no-op on a running batcher.

        The stranded sweep here is load-bearing, not an optimization:
        a request mid-dispatch at the moment of death is already marked
        RUNNING, so revive's backlog cancellation cannot touch it, and
        the supervisor's liveness poll only catches it while the
        replica still reads as dead — if THIS handler revives first
        (routing-path detection racing the ~20 ms poll), ``svc.alive``
        flips back to True and the supervisor never sees the death,
        stranding the request until its deadline (forever, with none).
        Collecting the victims inside the death lock is exact: the
        replica is quarantined before revive, so no new request can be
        routed to it until its probation window opens."""
        svc = self._replicas[ix]
        stranded: list = []
        with self._death_locks[ix]:
            if svc.alive or self._stopped or ix in self._retired:
                return  # revived already / shutdown / orderly retirement
            self.registry.counter("resilience/replica_deaths").inc()
            self._health[ix].mark_dead()
            self._instant("replica_death", replica=ix)
            self._flight_event("replica_death", replica=ix)
            logger.warning("replica %d of %r died; quarantined, "
                           "reviving", ix, self.name)
            with self._lock:
                stranded = [(route, inner) for (route, ix2, inner, _p)
                            in self._inflight.values() if ix2 == ix]
            try:
                svc.revive()
                self.registry.counter("resilience/revivals").inc()
                self._flight_event("revival", replica=ix)
            except Exception:
                logger.exception("replica %d revive failed; it stays "
                                 "quarantined until the next probe", ix)
        # settle OUTSIDE the death lock: each settle runs _on_done →
        # failover → _pick on this thread, which may legally re-enter
        # this handler for another replica
        self._sweep_stranded(
            ix, f"replica {ix} of {self.name!r} died with this "
                f"request in flight", reason="death",
            stranded=stranded)

    # --------------------------------------------------------------- api
    def submit(self, x, *, timeout: Optional[float] = None,
               ctx=None) -> Future:
        """Route one request (≤ max_batch_size rows).  Returns a Future
        that ALWAYS resolves: result, explicit error, or
        ``ServiceOverloaded``/``DeadlineExceeded``.  ``timeout`` (or the
        set-level ``deadline_ms``) bounds the whole request including
        failovers.

        ``ctx``: optional :class:`~bigdl_tpu.telemetry.RequestContext`
        (minted here when ``request_tracing`` is on) — it accumulates
        the request's hop history across failovers; a caller that keeps
        a reference reads the full routing story after the future
        resolves."""
        if self._stopped:
            raise ServiceClosed(f"replica set {self.name!r} is stopped")
        deadline_s = (timeout if timeout is not None else self.deadline_s)
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        if ctx is None and self._request_tracing:
            from bigdl_tpu.telemetry.context import RequestContext
            ctx = RequestContext(deadline=deadline)
        route = _Route(x, Future(), deadline, self.max_retries, ctx=ctx)
        self._attempt(route, initial=True)
        return route.outer

    def predict(self, x, timeout: Optional[float] = None):
        """Blocking sugar over :meth:`submit`."""
        fut = self.submit(x, timeout=timeout)
        # the route deadline already bounds the future when set; the
        # extra result() timeout is a belt against a supervisor gap.
        # Its expiry is normalized to DeadlineExceeded — on py<3.11
        # concurrent.futures.TimeoutError is NOT builtin TimeoutError,
        # and callers must not need to know which timeout fired
        wait = timeout if timeout is not None else None
        try:
            return fut.result(wait)
        except FutureTimeoutError:
            if fut.done():
                # the future RESOLVED with its own timeout-family
                # error (DeadlineExceeded is a TimeoutError, and on
                # py>=3.11 FutureTimeoutError aliases it) — propagate
                # the real diagnosis untouched
                raise
            raise DeadlineExceeded(
                f"request to {self.name!r} still unresolved after a "
                f"{wait:.3f}s result wait" if wait is not None else
                f"request to {self.name!r} never resolved") from None

    @property
    def n_replicas(self) -> int:
        """ACTIVE replica count (retired slots excluded)."""
        return len(self._replicas) - len(self._retired)

    @property
    def total_slots(self) -> int:
        """Slot count including retired ones (index-stable)."""
        return len(self._replicas)

    def active_indices(self) -> List[int]:
        retired = self._retired
        return [i for i in range(len(self._replicas))
                if i not in retired]

    @property
    def max_batch_size(self) -> int:
        """The per-replica coalescing cap (resolved off replica 0 at
        construction and frozen — the wire frontend chunks against
        this)."""
        return self._service_kw["max_batch_size"]

    def replica(self, ix: int) -> InferenceService:
        return self._replicas[ix]

    def health_states(self) -> List[str]:
        return [h.state for h in self._health]

    # ------------------------------------------------------ elasticity
    def _grow_spec(self):
        """Per-row input spec a grown replica warms against: the
        construction-time spec, else the warmed row spec of any live
        replica (deferred-spec sets that have seen traffic), else None
        (the new replica warms on its first request)."""
        if self._input_spec is not None:
            return self._input_spec
        for i in self.active_indices():
            spec = self._replicas[i].row_spec
            if spec is not None:
                return spec
        return None

    def set_replica_count(self, n: int, *,
                          timeout: Optional[float] = None) -> dict:
        """Grow or shrink to ``n`` ACTIVE replicas (the autoscaler's
        actuator; also a manual ops lever).  Serialized — concurrent
        calls queue behind ``_scale_lock``.

        Growing builds each new replica fully warmed (AOT bucket
        compiles included) BEFORE admitting its slot to routing, so
        scale-up never serves a compile stall; retired slots are reused
        lowest-first.  Shrinking retires the highest active slot
        through the quarantine discipline: the slot stops receiving new
        routes immediately, its accepted backlog drains to completion
        (``timeout`` bounds the wait), and its executables/params are
        released.  Returns ``{"active", "added", "retired"}``."""
        n = int(n)
        if n < 1:
            raise ValueError(f"replica count must be >= 1: {n}")
        if self._stopped:
            raise ServiceClosed(
                f"replica set {self.name!r} is stopped")
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        added: List[int] = []
        retired: List[int] = []
        with self._scale_lock:
            while self.n_replicas < n:
                ix = (min(self._retired) if self._retired
                      else len(self._replicas))
                # warm OFF the routing path: nothing below touches
                # shared state until the slot is installed
                svc, h = self._build_replica(ix, self._grow_spec())
                with self._lock:
                    if ix < len(self._replicas):
                        # slot reuse: the retired flag (cleared LAST)
                        # keeps lock-free readers off the slot while
                        # both cells swap
                        self._health[ix] = h
                        self._replicas[ix] = svc
                    else:
                        # append order matters for the lock-free
                        # readers: _replicas is the DISCOVERY list
                        # (_pick enumerates it, then indexes _health /
                        # _death_locks), so the side tables must exist
                        # before the slot becomes discoverable
                        self._health.append(h)
                        self._death_locks.append(threading.Lock())
                        self._replicas.append(svc)
                    self._retired = self._retired - {ix}
                self.registry.counter("resilience/replicas_added").inc()
                self._instant("replica_added", replica=ix)
                self._flight_event("replica_added", replica=ix)
                added.append(ix)
            while self.n_replicas > n:
                ix = max(self.active_indices())
                self._retire_replica(ix, deadline)
                retired.append(ix)
        return {"active": self.n_replicas, "added": added,
                "retired": retired}

    def _retire_replica(self, ix: int,
                        deadline: Optional[float]) -> None:
        """Orderly scale-down of one slot: mark retired (no new routes
        — the same exclusion quarantine gets), drain the accepted
        backlog through the replica's own batcher, then release the
        executables.  Any request a wedged batcher leaves stranded past
        the deadline is failed over like a death, so accepted work
        NEVER dangles."""
        svc = self._replicas[ix]
        with self._lock:
            self._retired = self._retired | frozenset((ix,))
        self.registry.counter("resilience/replicas_retired").inc()
        self._instant("replica_retired", replica=ix)
        self._flight_event("replica_retired", replica=ix)
        remaining = (max(0.1, deadline - time.monotonic())
                     if deadline is not None else None)
        svc.stop(drain=True, timeout=remaining)
        # normally stop(drain=True) resolved everything and _on_done
        # already emptied this slot's inflight entries; a wedged
        # batcher that outlived the join timeout leaves stragglers —
        # fail them over (settle → _on_done → retry on a live replica)
        self._sweep_stranded(
            ix, f"replica {ix} of {self.name!r} retired with this "
                f"request still in flight", reason="retired")
        svc.release()

    def _sweep_stranded(self, ix: int, message: str, reason: str,
                        stranded=None) -> None:
        """Fail over every in-flight request still pinned to replica
        ``ix`` — the ONE implementation shared by the death handler and
        the retirement path (each settle runs _on_done → failover on
        this thread).  The death handler passes its own ``stranded``
        list, collected inside the death lock where quarantine blocks
        new routes (the exactness argument in _on_replica_dead); the
        retirement path collects here, after its drain.  Every victim
        lands in the flight recorder as a ``stranded_failover`` so the
        retry is explicable post-mortem."""
        if stranded is None:
            with self._lock:
                stranded = [(route, inner)
                            for (route, ix2, inner, _p)
                            in self._inflight.values() if ix2 == ix]
        for route, inner in stranded:
            if not inner.done():
                if _settle(inner, exc=ReplicaDeadError(message)):
                    trace_id = (route.ctx.trace_id
                                if route.ctx is not None else None)
                    self._flight_event("stranded_failover",
                                       trace_id=trace_id, replica=ix,
                                       reason=reason)

    def health_snapshot(self) -> dict:
        """The ``/healthz`` provider: per-replica liveness + health
        states, ``ok`` iff every ACTIVE replica is alive and
        un-quarantined (retired slots are an orderly state, not an
        incident).  ``active`` is computed FIRST: a concurrent grow
        appending slot N must not make a health probe index past the
        lists it snapshotted (an autoscale event is not a 500)."""
        active = self.active_indices()
        replicas = []
        for i in active:
            svc = self._replicas[i]
            replicas.append({"ix": i, "alive": svc.alive,
                             "state": self._health[i].state,
                             "queue_depth": svc.queue_depth()})
        return {
            "ok": all(r["alive"] and r["state"] != QUARANTINED
                      for r in replicas),
            "model": self.name,
            "replicas": replicas,
            "retired_slots": sorted(self._retired),
        }

    def start(self) -> None:
        self._started = True
        retired = self._retired
        for i, svc in enumerate(self._replicas):
            if i not in retired:
                svc.start()

    def stats(self) -> dict:
        """Set-level snapshot: per-replica service stats + health, the
        resilience counters, and the ``aggregate`` view — summed
        counters, set-level throughput over the UNION of the replicas'
        activity windows, and latency percentiles over the
        concatenated reservoir windows (``ServingMetrics.aggregate``;
        the window-bias audit — NOT replica 0's numbers and NOT a sum
        of per-replica rates with mismatched denominators)."""
        from bigdl_tpu.serving.metrics import ServingMetrics
        active = self.active_indices()
        return {
            "model": self.name,
            "replicas": [
                {"ix": i, "alive": self._replicas[i].alive,
                 "health": self._health[i].snapshot(),
                 **self._replicas[i].stats()}
                for i in active],
            "retired_slots": sorted(self._retired),
            "aggregate": ServingMetrics.aggregate(
                [self._replicas[i].metrics for i in active],
                queue_depth=sum(self._replicas[i].queue_depth()
                                for i in active)),
            "resilience": self.registry.snapshot()["counters"],
        }

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            self._wake.notify_all()
        for svc in self._replicas:
            svc.stop(drain=drain, timeout=timeout)
        if self._supervisor is not None:
            self._supervisor.join(timeout=2.0)
        # deregister from the admin plane: a retired set left behind
        # would report its parked replicas as a permanent /healthz 503
        if self._admin_name is not None:
            from bigdl_tpu.telemetry import admin as _admin
            _srv = _admin.current()
            if _srv is not None:
                _srv.remove_source(self._admin_name)

    def __enter__(self) -> "ReplicaSet":
        return self

    def __exit__(self, *exc) -> None:
        self.stop(drain=True)
