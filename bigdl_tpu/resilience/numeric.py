"""Numeric-failure policy shared by the training drivers.

The driver's non-finite guard (``Optimizer.set_numeric_guard`` /
``Config.numeric_guard``) detects a NaN/Inf loss or gradient **at the
replay boundary** — the per-step finite flags ride the same
one-block-behind fetch as the loss vector, so the guard adds no host
sync (the GL107 discipline; graftlint catalog note "the numeric guard
rides the replay boundary").  Policies:

- ``"off"`` (default) — provably inert: the step function and the
  replay fetch are built exactly as before (bitwise loss sequences,
  equal dispatch counts; gated in ``tests/test_resilience.py``);
- ``"skip"`` — the jit'd step gates its own update: on a non-finite
  loss/grad the params/model-state/optimizer-state updates are
  ``jnp.where``-selected away on device (the dynamic-loss-scaling skip
  idiom), the step is counted in ``resilience/steps_skipped`` and
  training continues;
- ``"rollback"`` — the replay raises :class:`NonFiniteStepError`; the
  optimizer restores the latest VALID snapshot
  (``CheckpointManager.latest_valid`` — PR 7) and re-runs, bounded by
  ``Config.failure_retry_times`` (automatic loss-spike recovery);
- ``"abort"`` — the replay raises and nothing catches it: the run fails
  loudly at the exact iteration (the reference's debug posture).
"""

from __future__ import annotations

NUMERIC_POLICIES = ("off", "skip", "rollback", "abort")


class NonFiniteStepError(RuntimeError):
    """A training step produced a non-finite loss or gradient and the
    numeric-guard policy wants the run stopped (``rollback`` — caught by
    the optimizer's restore loop — or ``abort`` — surfaced to the
    caller)."""

    def __init__(self, step: int, loss: float, policy: str):
        self.step = int(step)
        self.loss = float(loss)
        self.policy = policy
        super().__init__(
            f"non-finite training step at iteration {step} "
            f"(loss={loss}); numeric_guard policy is {policy!r}")


def validate_policy(policy: str, source: str = "numeric_guard") -> str:
    if policy not in NUMERIC_POLICIES:
        raise ValueError(
            f"{source} must be one of {NUMERIC_POLICIES}, got {policy!r}")
    return policy
