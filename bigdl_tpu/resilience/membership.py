"""Cluster membership epochs — the roster layer under elastic training.

Reference: BigDL 2.0's position that the pipeline must assume the
cluster under it can change shape (arXiv:2204.01715), and the ZeRO
observation that the reduce-scatter/owned-slice/all-gather protocol is
world-size-parameterized (arXiv:2004.13336) — gradient SUMS are
invariant under resharding, so a training run can shrink or regrow
without changing its loss trajectory at a replay boundary.

One :class:`ClusterMembership` instance tracks a monotonically
increasing **membership epoch**.  Each epoch freezes a device roster (a
prefix of the devices the layer was armed with); a preemption signal,
an injected ``host_loss``/``device_loss`` fault, or an explicit
``request_resize`` opens the next epoch.  The training driver compares
``epoch()`` against the epoch it dispatched under at the replay
boundary it already crosses (the one-block-behind fetch) — detecting a
resize costs **zero additional host synchronization**.

Change semantics mirror PR-7 preemption handling:

- *graceful* (``request_resize``, ``host_loss`` with warning): the
  driver replays the in-flight block, writes a final synchronous
  snapshot, then resumes on the new roster — ``steps_lost_to_resize``
  is 0;
- *abrupt* (``device_loss``): the in-flight block is abandoned (its
  device buffers are gone by assumption) and the run resumes from
  ``latest_valid()`` — steps since that snapshot are the measured loss.

The layer is host-side bookkeeping only (no jax imports): rosters are
opaque device objects, epochs are ints, and every mutation is behind
one lock so signal handlers, fault-injection sites, and the driver
thread can race safely.  Like every resilience feature it is provably
inert when off — no ``ClusterMembership`` object exists unless a fault
plan or an explicit ``set_elastic()`` arms one, gated in
``tests/test_membership.py``.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple


class MembershipChanged(RuntimeError):
    """Raised by the training driver when it observes a membership epoch
    newer than the one it dispatched under.  Carries everything the
    elastic resume path needs: the target epoch, whether the transition
    was graceful (in-flight block replayed + snapshotted) and the
    driver's position at detection time (for ``steps_lost_to_resize``).
    """

    def __init__(self, epoch: "MembershipEpoch", graceful: bool,
                 detected_neval: int, t0: float):
        super().__init__(
            f"membership epoch {epoch.epoch}: world {epoch.world} "
            f"({epoch.reason}, {'graceful' if graceful else 'abrupt'})")
        self.epoch = epoch
        self.graceful = graceful
        self.detected_neval = detected_neval
        self.t0 = t0  # monotonic detection time → resize_downtime_s


class MembershipEpoch:
    """One frozen roster.  Immutable after construction — readers hold
    a reference without the membership lock."""

    __slots__ = ("epoch", "devices", "world", "reason", "graceful")

    def __init__(self, epoch: int, devices: Tuple, reason: str,
                 graceful: bool):
        self.epoch = int(epoch)
        self.devices = tuple(devices)
        self.world = len(self.devices)
        self.reason = reason
        self.graceful = bool(graceful)

    def __repr__(self):
        return (f"MembershipEpoch(epoch={self.epoch}, world={self.world},"
                f" reason={self.reason!r}, graceful={self.graceful})")


class ClusterMembership:
    """Monotonic membership epochs over a fixed device pool.

    Armed with the full device list; every epoch's roster is a prefix
    of it (a shrink keeps the lowest-indexed survivors, a regrow
    re-admits the departed tail — the single-host analog of pod
    re-provisioning, and exactly what ``Mesh(np.array(roster))``
    rebuilding needs).  ``epoch()`` is designed to be polled from the
    driver's hot loop: one lock acquisition, no allocation.
    """

    def __init__(self, devices: Sequence, registry=None, recorder=None):
        pool = tuple(devices)
        if not pool:
            raise ValueError("ClusterMembership needs >= 1 device")
        self._pool = pool
        self._registry = registry
        self._recorder = recorder
        self._lock = threading.Lock()
        # the epoch ledger: append-only history of frozen rosters
        # guarded-by: _lock
        self._epochs: List[MembershipEpoch] = [
            MembershipEpoch(1, pool, "initial", True)]
        self._emit(self._epochs[0])

    # ------------------------------------------------------------- reads
    def epoch(self) -> int:
        """Current epoch number (driver hot-loop poll)."""
        with self._lock:
            return self._epochs[-1].epoch

    def current(self) -> MembershipEpoch:
        with self._lock:
            return self._epochs[-1]

    def history(self) -> List[MembershipEpoch]:
        with self._lock:
            return list(self._epochs)

    def pool_size(self) -> int:
        return len(self._pool)

    def changed_since(self, epoch: int) -> Optional[MembershipEpoch]:
        """The newest epoch if it is newer than ``epoch``, else None —
        the driver's replay-boundary check, one lock round-trip."""
        with self._lock:
            cur = self._epochs[-1]
        # the epoch ledger is the control plane's broadcast: every host
        # observes the same ledger, so the driver's resize branch is
        # uniform at its replay boundary
        # replicated-by: membership-epoch-ledger
        return cur if cur.epoch > epoch else None

    # ----------------------------------------------------------- signals
    def request_resize(self, world: int,
                       reason: str = "resize") -> MembershipEpoch:
        """Graceful resize to ``world`` devices (explicit operator/plan
        request).  No-op returning the current epoch when the roster
        already has that size."""
        return self._open(world, reason, graceful=True)

    def signal_host_loss(self, to: Optional[int] = None) -> MembershipEpoch:
        """A host received its preemption warning: graceful shrink (the
        warning window is long enough to replay + snapshot).  Default
        target: half the current world, floor 1."""
        with self._lock:
            cur = self._epochs[-1].world
        return self._open(to if to is not None else max(1, cur // 2),
                          "host_loss", graceful=True)

    def signal_device_loss(self,
                           to: Optional[int] = None) -> MembershipEpoch:
        """A device vanished without warning: abrupt shrink — the
        in-flight block is unrecoverable.  Default target: current
        world minus one, floor 1."""
        with self._lock:
            cur = self._epochs[-1].world
        return self._open(to if to is not None else max(1, cur - 1),
                          "device_loss", graceful=False)

    # ------------------------------------------------------------ intern
    def _open(self, world: int, reason: str,
              graceful: bool) -> MembershipEpoch:
        world = int(world)
        # replicated-by: membership-epoch-ledger
        if not 1 <= world <= len(self._pool):
            raise ValueError(
                f"resize target {world} outside [1, {len(self._pool)}] "
                f"(the armed device pool bounds every roster)")
        with self._lock:
            cur = self._epochs[-1]
            # replicated-by: membership-epoch-ledger
            if cur.world == world:
                return cur  # roster unchanged — no epoch churn
            nxt = MembershipEpoch(cur.epoch + 1, self._pool[:world],
                                  reason, graceful)
            self._epochs.append(nxt)
        self._emit(nxt)
        return nxt

    def _emit(self, ep: MembershipEpoch) -> None:
        if self._registry is not None:
            self._registry.gauge(
                "resilience/membership_epoch").set(ep.epoch)
        if self._recorder is not None:
            self._recorder.record(
                "membership_epoch", cat="resilience", epoch=ep.epoch,
                world=ep.world, reason=ep.reason, graceful=ep.graceful)

    def describe(self) -> str:
        with self._lock:
            eps = list(self._epochs)
        return " -> ".join(f"e{e.epoch}:w{e.world}({e.reason})"
                           for e in eps)


def monotonic() -> float:
    """Detection-time clock for ``MembershipChanged.t0`` (separated so
    tests can monkeypatch downtime measurement deterministically)."""
    return time.monotonic()
