"""bigdl_tpu.resilience — designed-in failure handling.

Three layers, one discipline (failures are expected events, and every
degradation path is gated by a deterministic test):

- :mod:`~bigdl_tpu.resilience.faults` — seeded, scoped, provably-inert
  fault injection (``Config.fault_plan`` / ``BIGDL_TPU_FAULT_PLAN``);
- :mod:`~bigdl_tpu.resilience.replica_set` — self-healing
  replica-per-device serving: least-queue-depth routing, per-replica
  health quarantine/probation, deadlines, bounded failover retry, load
  shedding with retry-after;
- :mod:`~bigdl_tpu.resilience.numeric` — the training driver's
  non-finite loss/grad guard policies (``skip`` | ``rollback`` |
  ``abort``) riding the one-block-behind fetch;
- :mod:`~bigdl_tpu.resilience.membership` — monotonic membership
  epochs under elastic training: each epoch freezes a device roster,
  and the driver detects roster changes at the replay boundary it
  already crosses.

``ReplicaSet`` is imported lazily (PEP 562) so training-only processes
never pay the serving import; the membership layer is lazy for the
same reason (it only exists on elastic runs).
"""

from bigdl_tpu.resilience.faults import (FaultClause, FaultInjector,
                                         InjectedFault,
                                         ReplicaDeathFault,
                                         parse_fault_plan)
from bigdl_tpu.resilience.health import (CircuitBreaker, HealthPolicy,
                                         ReplicaHealth)
from bigdl_tpu.resilience.numeric import (NUMERIC_POLICIES,
                                          NonFiniteStepError)

__all__ = [
    "FaultClause", "FaultInjector", "InjectedFault", "ReplicaDeathFault",
    "parse_fault_plan", "CircuitBreaker", "HealthPolicy", "ReplicaHealth",
    "NUMERIC_POLICIES", "NonFiniteStepError", "ReplicaSet",
    "ReplicaDeadError", "ClusterMembership", "MembershipChanged",
    "MembershipEpoch",
]

_LAZY = {"ReplicaSet", "ReplicaDeadError"}
_LAZY_MEMBERSHIP = {"ClusterMembership", "MembershipChanged",
                    "MembershipEpoch"}


def __getattr__(name):
    if name in _LAZY:
        from bigdl_tpu.resilience import replica_set
        return getattr(replica_set, name)
    if name in _LAZY_MEMBERSHIP:
        from bigdl_tpu.resilience import membership
        return getattr(membership, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
