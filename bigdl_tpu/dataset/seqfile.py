"""Hadoop SequenceFile reader/writer (uncompressed, Text/Bytes records).

Reference: the ImageNet ingestion pipeline —
``DL/models/utils/ImageNetSeqFileGenerator.scala`` packs images into
sequence files via ``BGRImgToLocalSeqFile`` (key = ``Text``
``"<name>\\n<label>"`` or ``"<label>"``, value = ``Text`` image bytes),
and training reads them back with ``LocalSeqFileToBytes``.  The TPU build
reads/writes the same container so reference-generated datasets feed it
unchanged — without Hadoop: the uncompressed SequenceFile layout is
simple enough to speak directly.

Format (all big-endian):
  header:  b"SEQ" + version byte (6), key class (Hadoop Text string),
           value class, bool compressed, bool blockCompressed,
           metadata count (int32) + pairs, 16-byte sync marker
  record:  recordLen int32, keyLen int32, key bytes, value bytes;
           recordLen == -1 → 16-byte sync marker follows
  Text payloads start with a Hadoop VInt length.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

_VERSION = 6
TEXT = "org.apache.hadoop.io.Text"
BYTES_WRITABLE = "org.apache.hadoop.io.BytesWritable"
DEFAULT_CODEC = "org.apache.hadoop.io.compress.DefaultCodec"


# ----------------------------------------------------------- hadoop VInt
def read_vint(buf: bytes, pos: int) -> Tuple[int, int]:
    """Hadoop WritableUtils.readVInt → (value, new_pos)."""
    first = struct.unpack_from("b", buf, pos)[0]
    pos += 1
    if first >= -112:
        return first, pos
    if first >= -120:
        n = -(first + 112)
        neg = False
    else:
        n = -(first + 120)
        neg = True
    v = 0
    for _ in range(n):
        v = (v << 8) | buf[pos]
        pos += 1
    return (~v if neg else v), pos


def write_vint(v: int) -> bytes:
    if -112 <= v <= 127:
        return struct.pack("b", v)
    neg = v < 0
    if neg:
        v = ~v
    n = (v.bit_length() + 7) // 8
    first = (-112 - n) if not neg else (-120 - n)
    return struct.pack("b", first) + v.to_bytes(n, "big")


def _hadoop_string(s: str) -> bytes:
    b = s.encode()
    return write_vint(len(b)) + b


def _read_hadoop_string(f) -> str:
    # VInt length then bytes; VInt is at most 5 bytes here
    head = f.read(1)
    first = struct.unpack("b", head)[0]
    if first >= -112:
        n = first
    else:
        ln = -(first + 112) if first >= -120 else -(first + 120)
        n = int.from_bytes(f.read(ln), "big")
    return f.read(n).decode()


def _decode_text(payload: bytes) -> bytes:
    """Text serialization = VInt byte-length + utf8 bytes."""
    n, pos = read_vint(payload, 0)
    return payload[pos:pos + n]


def _decode_bytes_writable(payload: bytes) -> bytes:
    (n,) = struct.unpack_from(">i", payload, 0)
    return payload[4:4 + n]


# ------------------------------------------------------------------ reader
def read_seqfile(path: str) -> Iterator[Tuple[bytes, bytes]]:
    """Yield (key_bytes, value_bytes) decoded per the header's classes."""
    with open(path, "rb") as f:
        magic = f.read(3)
        if magic != b"SEQ":
            raise IOError(f"{path} is not a SequenceFile")
        version = f.read(1)[0]
        if version < 6:
            # v5 lacks the metadata section this parser expects
            raise NotImplementedError(
                f"SequenceFile version {version}; only v6 is supported")
        key_cls = _read_hadoop_string(f)
        val_cls = _read_hadoop_string(f)
        compressed = f.read(1)[0] != 0
        block = f.read(1)[0] != 0
        codec = None
        if compressed:
            codec = _read_hadoop_string(f)
            if codec != DEFAULT_CODEC:
                raise NotImplementedError(
                    f"SequenceFile codec {codec!r}: only DefaultCodec "
                    "(zlib) record compression is supported")
        (meta_count,) = struct.unpack(">i", f.read(4))
        for _ in range(meta_count):
            _read_hadoop_string(f)
            _read_hadoop_string(f)
        sync = f.read(16)

        def decode(cls, payload):
            if cls == TEXT:
                return _decode_text(payload)
            if cls == BYTES_WRITABLE:
                return _decode_bytes_writable(payload)
            return payload

        if block:
            # block compression (SequenceFile.BlockCompressWriter): each
            # block = sync escape + sync, VInt record count, then four
            # length-prefixed zlib buffers (key lengths, keys, value
            # lengths, values); the length buffers hold VInts
            yield from _read_blocks(f, sync, key_cls, val_cls, decode,
                                    path)
            return
        while True:
            head = f.read(4)
            if len(head) < 4:
                return
            (rec_len,) = struct.unpack(">i", head)
            if rec_len == -1:   # sync marker
                marker = f.read(16)
                if marker != sync:
                    raise IOError(f"corrupt sync marker in {path}")
                continue
            (key_len,) = struct.unpack(">i", f.read(4))
            key = f.read(key_len)
            value = f.read(rec_len - key_len)
            if len(key) != key_len or len(value) != rec_len - key_len:
                raise IOError(f"truncated SequenceFile record in {path}")
            if compressed:
                # record compression: the VALUE payload is deflated
                value = zlib.decompress(value)
            yield decode(key_cls, key), decode(val_cls, value)


def _read_vint_stream(f) -> int:
    """Hadoop WritableUtils.readVInt straight off a stream (shares the
    byte-level decoder with :func:`read_vint` — the first byte tells how
    many more to pull)."""
    first = f.read(1)
    if len(first) < 1:
        raise IOError("truncated SequenceFile: EOF inside a VInt")
    lead = struct.unpack("b", first)[0]
    extra = 0
    if lead < -112:
        extra = -(lead + 120) if lead < -120 else -(lead + 112)
    rest = f.read(extra)
    if len(rest) < extra:
        raise IOError("truncated SequenceFile: EOF inside a VInt")
    value, _ = read_vint(first + rest, 0)
    return value


def _vints(buf: bytes):
    pos = 0
    while pos < len(buf):
        v, pos = read_vint(buf, pos)
        yield v


def _read_blocks(f, sync, key_cls, val_cls, decode, path):
    while True:
        head = f.read(4)
        if len(head) < 4:
            return
        (esc,) = struct.unpack(">i", head)
        if esc != -1 or f.read(16) != sync:
            raise IOError(f"corrupt block sync in {path}")
        n_records = _read_vint_stream(f)

        def buf():
            ln = _read_vint_stream(f)
            return zlib.decompress(f.read(ln))

        key_lens = list(_vints(buf()))
        keys = buf()
        val_lens = list(_vints(buf()))
        vals = buf()
        if len(key_lens) != n_records or len(val_lens) != n_records:
            raise IOError(f"block record-count mismatch in {path}")
        kp = vp = 0
        for kl, vl in zip(key_lens, val_lens):
            yield (decode(key_cls, keys[kp:kp + kl]),
                   decode(val_cls, vals[vp:vp + vl]))
            kp += kl
            vp += vl


def write_seqfile(path: str, records: Sequence[Tuple[bytes, bytes]],
                  key_cls: str = TEXT, val_cls: str = TEXT,
                  sync_interval: int = 100,
                  compressed: bool = False,
                  block_compressed: bool = False) -> None:
    """Write (key, value) byte pairs as a SequenceFile
    (``BGRImgToLocalSeqFile`` analog); ``compressed=True`` uses Hadoop
    record compression with DefaultCodec (zlib) on the values;
    ``block_compressed=True`` writes the block format (one zlib buffer
    per ``sync_interval`` records — what MapReduce jobs emit by
    default)."""
    sync = np.random.default_rng(12345).bytes(16)

    def encode(cls, payload: bytes) -> bytes:
        if cls == TEXT:
            return write_vint(len(payload)) + payload
        if cls == BYTES_WRITABLE:
            return struct.pack(">i", len(payload)) + payload
        return payload

    with open(path, "wb") as f:
        f.write(b"SEQ" + bytes([_VERSION]))
        f.write(_hadoop_string(key_cls))
        f.write(_hadoop_string(val_cls))
        on = compressed or block_compressed
        f.write(bytes([1 if on else 0, 1 if block_compressed else 0]))
        if on:
            f.write(_hadoop_string(DEFAULT_CODEC))
        f.write(struct.pack(">i", 0))   # no metadata
        f.write(sync)
        if block_compressed:
            recs = list(records)
            for start in range(0, len(recs), sync_interval):
                chunk = recs[start:start + sync_interval]
                kl = b"".join(write_vint(len(encode(key_cls, k)))
                              for k, _ in chunk)
                kb = b"".join(encode(key_cls, k) for k, _ in chunk)
                vl = b"".join(write_vint(len(encode(val_cls, v)))
                              for _, v in chunk)
                vb = b"".join(encode(val_cls, v) for _, v in chunk)
                f.write(struct.pack(">i", -1))
                f.write(sync)
                f.write(write_vint(len(chunk)))
                for payload in (kl, kb, vl, vb):
                    z = zlib.compress(payload)
                    f.write(write_vint(len(z)))
                    f.write(z)
            return
        for i, (k, v) in enumerate(records):
            if i and i % sync_interval == 0:
                f.write(struct.pack(">i", -1))
                f.write(sync)
            ke = encode(key_cls, k)
            ve = encode(val_cls, v)
            if compressed:
                ve = zlib.compress(ve)
            f.write(struct.pack(">i", len(ke) + len(ve)))
            f.write(struct.pack(">i", len(ke)))
            f.write(ke)
            f.write(ve)


# ------------------------------------------------- reference key convention
def parse_imagenet_key(key: bytes) -> Tuple[Optional[str], int]:
    """``"<name>\\n<label>"`` or ``"<label>"`` → (name, label)
    (``BGRImgToLocalSeqFile.scala:67-69``)."""
    s = key.decode()
    if "\n" in s:
        name, label = s.rsplit("\n", 1)
        return name, int(label)
    return None, int(s)


def seqfiles_to_byte_records(paths: Sequence[str]
                             ) -> Iterator[Tuple[int, bytes]]:
    """Stream (label, image_bytes) from sequence files
    (``LocalSeqFileToBytes`` analog)."""
    for p in paths:
        for key, value in read_seqfile(p):
            _, label = parse_imagenet_key(key)
            yield label, value
