"""Multi-worker batch assembly + prefetch.

Reference: ``DL/dataset/image/MTLabeledBGRImgToBatch.scala`` and
``DL/transform/vision/image/MTImageFeatureToBatch.scala`` — the reference
keeps N Spark-executor cores busy decoding/augmenting while training runs,
assembling MiniBatches on a parallel pipeline.

TPU redesign (SURVEY §7 stage 5 risk "input pipeline throughput"): the
same role on a TPU-VM host — per-sample preprocessing fanned out over a
thread pool (numpy releases the GIL in its kernels) + a bounded
prefetch queue so batch ``i+1`` is assembled while the jit'd step runs
batch ``i``.  Composes as a normal Transformer:

    dataset >> MTSampleToMiniBatch(128, per_sample_fn, workers=8)
"""

from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.sample import Sample, MiniBatch
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.imgops import sample_key


def _stack(samples) -> MiniBatch:
    feats = np.stack([s.feature for s in samples])
    if samples[0].label is None:
        return MiniBatch(feats, None)
    return MiniBatch(feats, np.stack([np.asarray(s.label)
                                      for s in samples]))


class MTSampleToMiniBatch(Transformer):
    """Parallel per-sample transform + batch assembly + prefetch.

    ``transform`` maps one Sample → Sample (e.g. a composed augmentation
    pipeline applied per element); it runs on ``workers`` threads.  Up to
    ``prefetch`` assembled batches are buffered ahead of the consumer.
    """

    def __init__(self, batch_size: int,
                 transform: Optional[Callable[[Sample], Sample]] = None,
                 workers: int = 4, prefetch: int = 2,
                 drop_remainder: bool = True):
        self.batch_size = batch_size
        self.transform = transform
        self.workers = workers
        self.prefetch = max(1, prefetch)
        self.drop_remainder = drop_remainder
        # per-instance pass counter folded into the sample key: calling
        # the SAME transformer once per epoch over a fixed-order dataset
        # must still draw fresh augmentation each epoch (run-to-run
        # deterministic, pass-to-pass varying)
        self._passes = itertools.count()

    def __call__(self, it: Iterator[Sample]) -> Iterator[MiniBatch]:
        out_q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        _END = object()

        def put_or_stop(item) -> bool:
            """Bounded put that stays responsive to consumer shutdown —
            a consumer that exits early must not leave this thread blocked
            on a full queue forever."""
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        pass_ix = next(self._passes)

        def keyed_transform(ix_sample):
            # bracket the transform in the stream position so ThreadRng
            # draws are a pure function of (seed, pass, sample index) —
            # run-to-run deterministic no matter which worker thread
            # executes it
            ix, sample = ix_sample
            with sample_key((pass_ix << 40) | ix):
                return self.transform(sample)

        def producer():
            pool = ThreadPoolExecutor(max_workers=self.workers)
            stream_ix = 0
            try:
                buf = []
                # map the per-sample transform with bounded lookahead:
                # chunks of one batch keep memory flat
                src = iter(it)
                while not stop.is_set():
                    chunk = []
                    try:
                        for _ in range(self.batch_size):
                            chunk.append(next(src))
                    except StopIteration:
                        pass
                    if not chunk:
                        break
                    if self.transform is not None:
                        chunk = list(pool.map(
                            keyed_transform,
                            enumerate(chunk, start=stream_ix)))
                    stream_ix += len(chunk)
                    buf.extend(chunk)
                    while len(buf) >= self.batch_size:
                        if not put_or_stop(_stack(buf[:self.batch_size])):
                            return
                        buf = buf[self.batch_size:]
                    if len(chunk) < self.batch_size:
                        break
                if buf and not self.drop_remainder:
                    put_or_stop(_stack(buf))
            except BaseException as e:  # surface worker errors to consumer
                put_or_stop(e)
            finally:
                pool.shutdown(wait=False)
                # _END must be DELIVERED, not best-effort: a put_nowait
                # here can hit a momentarily-full queue while the consumer
                # is alive and leave it blocked on get() forever.  The
                # stop-aware bounded put gives up only once the consumer
                # has exited (stop set in its finally).
                put_or_stop(_END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = out_q.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            # drain so the producer can observe `stop` and exit
            while True:
                try:
                    out_q.get_nowait()
                except queue.Empty:
                    break
