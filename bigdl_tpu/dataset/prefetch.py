"""Multi-worker batch assembly + prefetch.

Reference: ``DL/dataset/image/MTLabeledBGRImgToBatch.scala`` and
``DL/transform/vision/image/MTImageFeatureToBatch.scala`` — the reference
keeps N Spark-executor cores busy decoding/augmenting while training runs,
assembling MiniBatches on a parallel pipeline.

TPU redesign (SURVEY §7 stage 5 risk "input pipeline throughput"): the
same role on a TPU-VM host — per-sample preprocessing fanned out over a
thread pool (numpy releases the GIL in its kernels) + a bounded
prefetch queue so batch ``i+1`` is assembled while the jit'd step runs
batch ``i``.  Composes as a normal Transformer:

    dataset >> MTSampleToMiniBatch(128, per_sample_fn, workers=8)

The pipeline has TWO prefetch stages since the fused-dispatch rework:

1. host assembly (this transformer): samples → MiniBatches on worker
   threads, buffered in a bounded queue;
2. device staging (:class:`DeviceBlockStager`): consecutive MiniBatches
   → one host-stacked K-step block → asynchronously ``device_put`` so
   block ``i+1`` is already landing in HBM (sharded, for the SPMD
   path) while the jit'd K-step scan crunches block ``i``.
"""

from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.sample import Sample, MiniBatch
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.telemetry.tracer import NULL_SPAN as _NOOP_CM
from bigdl_tpu.utils.imgops import sample_key


def _leaf_meta(leaf):
    return (tuple(np.shape(leaf)), getattr(leaf, "dtype", None))


def batch_signature(batch: MiniBatch):
    """Structural identity of a batch — pytree structure + per-leaf
    shape/dtype.  Blocks only stack batches with identical signatures
    (a ragged remainder batch, or a bucket change in a padded text/COO
    pipeline, ends the block instead of crashing ``np.stack``)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(
        (batch.input, batch.target))
    return treedef, tuple(_leaf_meta(l) for l in leaves)


class DeviceBlockStager:
    """Device-prefetch stage: pulls MiniBatches from the host pipeline,
    stacks up to ``k`` of them along a new leading step axis, and hands
    the stack to ``place_block`` (``jnp.asarray`` tree locally; a
    ``P(None, "data")``-sharded global-array build under SPMD).

    ``jax.device_put``-family transfers are asynchronous, so a driver
    that stages block ``i+1`` right after dispatching block ``i`` gets
    the double-buffer for free: the host→HBM DMA of ``i+1`` overlaps
    the device compute of ``i``, and the jit dispatch never waits on a
    transfer.  The stager itself never looks at driver state — the
    driver passes a step cap (from the trigger probe) and a records
    budget (to the epoch boundary) per block, which is what keeps
    epoch/trigger semantics exact under fusion.
    """

    def __init__(self, batch_iter, place_block, tracer=None):
        self._it = batch_iter
        self._place = place_block
        self._held = None  # batch pulled but deferred to the next block
        # telemetry (optional): a bigdl_tpu.telemetry.Tracer records the
        # host-stack vs H2D-staging split of every take() — host-side
        # clock reads only, inert when None
        self._tracer = tracer

    def reset(self, batch_iter) -> None:
        """Point at a fresh iterator (epoch rollover: the driver
        shuffles and re-opens the dataset, exactly like the unfused
        loop did).  Never called with lookahead in flight — blocks are
        budgeted to stop AT the epoch boundary, so the stager holds no
        stale pre-shuffle batches."""
        close = getattr(self._it, "close", None)
        if close is not None:
            close()
        self._it = batch_iter
        self._held = None

    def take(self, k: int, records_budget: int):
        """Stage the next block: up to ``k`` consecutive same-signature
        batches whose cumulative size stays within ``records_budget``
        (the batch that reaches the budget — the epoch-boundary step —
        is included; the NEXT pull would belong to the next epoch).

        Returns ``(dev_xs, dev_ys, sizes)`` where dev arrays carry a
        leading ``len(sizes)`` step axis and ``dev_ys`` is None for
        unlabelled batches.  Raises StopIteration if the host pipeline
        is exhausted with nothing staged (finite iterator misuse — the
        training contract is an infinite shuffled stream)."""
        tr = self._tracer
        span = tr.span if tr is not None else None
        with span("host_stack", cat="stage") if span else _NOOP_CM:
            batches = []
            sig = None
            total = 0
            while len(batches) < max(1, int(k)) and total < records_budget:
                if self._held is not None:
                    b, self._held = self._held, None
                else:
                    try:
                        b = next(self._it)
                    except StopIteration:
                        break
                if not isinstance(b, MiniBatch):
                    raise TypeError(
                        "training dataset must yield MiniBatch (attach "
                        "SampleToMiniBatch / MTSampleToMiniBatch)")
                b_sig = batch_signature(b)
                if sig is None:
                    sig = b_sig
                elif b_sig != sig:
                    self._held = b  # ragged/bucket change: next block's
                    break           # head
                batches.append(b)
                total += b.size()
            if not batches:
                raise StopIteration(
                    "training data iterator exhausted mid-epoch — "
                    "train=True iterators must be infinite (see "
                    "AbstractDataSet.data)")
            import jax
            tmap = jax.tree_util.tree_map
            xs = tmap(lambda *ls: np.stack([np.asarray(l) for l in ls]),
                      *[b.input for b in batches])
            if batches[0].target is None:
                ys = None
            else:
                ys = tmap(lambda *ls: np.stack([np.asarray(l) for l in ls]),
                          *[b.target for b in batches])
        with span("h2d_stage", cat="stage", k=len(batches)) if span \
                else _NOOP_CM:
            # the device_put underneath is ASYNCHRONOUS — this span times
            # the host-side staging cost, not the DMA itself (the DMA
            # overlaps the in-flight block's compute by design)
            dev_xs, dev_ys = self._place(xs, ys)
        return dev_xs, dev_ys, [b.size() for b in batches]


def fast_forward_records(batch_iter, skip: int) -> int:
    """Advance a fresh epoch iterator past exactly ``skip`` records
    (the mid-epoch resume fast-forward).  Scale-aware callers divide
    the GLOBAL records counter by their per-step record scale first —
    under an elastic resume each of P′ survivors skips its own 1/P′
    share through this one helper.

    Raises a targeted error when the batch boundaries cannot land on
    ``skip`` exactly: silently overshooting would replay the epoch
    from a position the loss trajectory never visited."""
    skipped = 0
    while skipped < skip:
        try:
            skipped += next(batch_iter).size()
        except StopIteration:
            raise ValueError(
                f"dataset fast-forward: epoch exhausted after "
                f"{skipped} records while seeking {skip} — the "
                f"dataset shrank since the snapshot was written"
            ) from None
    if skipped != skip:
        raise ValueError(
            f"dataset fast-forward: batch boundaries land on {skipped} "
            f"records, not the {skip} the snapshot recorded — batch "
            f"size or dataset layout changed since the snapshot was "
            f"written")
    return skipped


def _stack(samples) -> MiniBatch:
    feats = np.stack([s.feature for s in samples])
    if samples[0].label is None:
        return MiniBatch(feats, None)
    return MiniBatch(feats, np.stack([np.asarray(s.label)
                                      for s in samples]))


class MTSampleToMiniBatch(Transformer):
    """Parallel per-sample transform + batch assembly + prefetch.

    ``transform`` maps one Sample → Sample (e.g. a composed augmentation
    pipeline applied per element); it runs on ``workers`` threads.  Up to
    ``prefetch`` assembled batches are buffered ahead of the consumer.
    """

    def __init__(self, batch_size: int,
                 transform: Optional[Callable[[Sample], Sample]] = None,
                 workers: int = 4, prefetch: int = 2,
                 drop_remainder: bool = True):
        self.batch_size = batch_size
        self.transform = transform
        self.workers = workers
        self.prefetch = max(1, prefetch)
        self.drop_remainder = drop_remainder
        # per-instance pass counter folded into the sample key: calling
        # the SAME transformer once per epoch over a fixed-order dataset
        # must still draw fresh augmentation each epoch (run-to-run
        # deterministic, pass-to-pass varying)
        self._passes = itertools.count()

    def __call__(self, it: Iterator[Sample]) -> Iterator[MiniBatch]:
        out_q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        _END = object()

        def put_or_stop(item) -> bool:
            """Bounded put that stays responsive to consumer shutdown —
            a consumer that exits early must not leave this thread blocked
            on a full queue forever."""
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        pass_ix = next(self._passes)

        def keyed_transform(ix_sample):
            # bracket the transform in the stream position so ThreadRng
            # draws are a pure function of (seed, pass, sample index) —
            # run-to-run deterministic no matter which worker thread
            # executes it
            ix, sample = ix_sample
            with sample_key((pass_ix << 40) | ix):
                return self.transform(sample)

        # the producer's terminal error, recorded OUT of band: queue
        # delivery can fail (e.g. the pool itself refuses to start under
        # thread exhaustion), and the consumer must still be able to
        # surface the ORIGINAL error instead of blocking on get() forever
        failure: list = [None]

        def producer():
            pool = None
            stream_ix = 0
            try:
                # inside the try: a ThreadPoolExecutor that cannot start
                # (resource exhaustion) must take the error path below,
                # not kill this thread with the consumer still blocked
                pool = ThreadPoolExecutor(max_workers=self.workers)
                buf = []
                # map the per-sample transform with bounded lookahead:
                # chunks of one batch keep memory flat
                src = iter(it)
                while not stop.is_set():
                    chunk = []
                    try:
                        for _ in range(self.batch_size):
                            chunk.append(next(src))
                    except StopIteration:
                        pass
                    if not chunk:
                        break
                    if self.transform is not None:
                        chunk = list(pool.map(
                            keyed_transform,
                            enumerate(chunk, start=stream_ix)))
                    stream_ix += len(chunk)
                    buf.extend(chunk)
                    while len(buf) >= self.batch_size:
                        if not put_or_stop(_stack(buf[:self.batch_size])):
                            return
                        buf = buf[self.batch_size:]
                    if len(chunk) < self.batch_size:
                        break
                if buf and not self.drop_remainder:
                    put_or_stop(_stack(buf))
            except BaseException as e:  # surface worker errors to consumer
                failure[0] = e  # out-of-band first: survives a failed put
                put_or_stop(e)
            finally:
                # cancel queued per-sample work so idle workers exit now
                # instead of grinding through a chunk nobody will read
                if pool is not None:
                    pool.shutdown(wait=False, cancel_futures=True)
                # propagate shutdown upstream: in a chained pipeline the
                # source is itself a generator (possibly another MT
                # assembler) whose own cleanup must run NOW, on the one
                # thread that consumed it — not whenever GC finds it
                # (that is the thread-leak window the early-exit
                # regression tests pin down)
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:  # source cleanup must not mask
                        pass           # the original error/_END delivery
                # _END must be DELIVERED, not best-effort: a put_nowait
                # here can hit a momentarily-full queue while the consumer
                # is alive and leave it blocked on get() forever.  The
                # stop-aware bounded put gives up only once the consumer
                # has exited (stop set in its finally).
                put_or_stop(_END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                try:
                    # bounded get + liveness check: a producer thread
                    # that died without delivering _END (or its error)
                    # must surface on the next pull — the downstream
                    # DeviceBlockStager.take() sits directly on this
                    # generator, and an unbounded get() here would wedge
                    # the training driver forever
                    item = out_q.get(timeout=0.2)
                except queue.Empty:
                    if t.is_alive() or not out_q.empty():
                        continue
                    if failure[0] is not None:
                        raise failure[0]
                    raise RuntimeError(
                        "batch-assembly producer thread died without "
                        "delivering an end-of-stream marker or error")
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            # drain so the producer can observe `stop` and exit, then
            # reap it DETERMINISTICALLY: close()/throw() mid-epoch must
            # not leave the thread (or its queued batches) behind.  The
            # join is bounded — a producer stuck in a pathological
            # user transform stays a daemon and cannot hang teardown.
            while True:
                try:
                    # drained items are DATA batches discarded so the
                    # producer can observe `stop` — no futures ride
                    # this queue; graftlint: disable=GL203
                    out_q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)
            while True:  # items put during the join window
                try:
                    # same deliberate discard as above
                    # graftlint: disable=GL203
                    out_q.get_nowait()
                except queue.Empty:
                    break
