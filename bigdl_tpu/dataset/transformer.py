"""Transformers — composable preprocessing pipelines.

Reference: ``DL/dataset/Transformer.scala:44`` — ``Transformer[A,B]`` maps
``Iterator[A] → Iterator[B]`` and composes with ``->``
(``ChainedTransformer:88``); the public idiom is
``DataSet.array(...) -> BytesToGreyImg() -> GreyImgNormalizer(...) -> GreyImgToBatch(...)``
(``models/lenet/Train.scala:72-74``).

Python has no ``->`` operator; composition is ``>>`` (or ``.chain``):
``dataset >> BytesToGreyImg() >> GreyImgNormalizer(m, s) >> SampleToMiniBatch(b)``.
"""

from __future__ import annotations

import math
from typing import Iterable, Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.sample import (
    MiniBatch, PaddingParam, Sample, batch_samples,
)


class Transformer:
    """Iterator→Iterator stage; compose with ``>>``."""

    def __call__(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        return ChainedTransformer(self, other)

    def chain(self, other: "Transformer") -> "ChainedTransformer":
        return self >> other


class ChainedTransformer(Transformer):
    """(reference ``Transformer.scala:88``)"""

    def __init__(self, first: Transformer, second: Transformer):
        self.first, self.second = first, second

    def __call__(self, it):
        return self.second(self.first(it))


class FnTransformer(Transformer):
    """Map a per-element function (covers most one-off reference
    transformers)."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, it):
        return (self.fn(x) for x in it)


class SampleToMiniBatch(Transformer):
    """Group Samples into MiniBatches (reference
    ``Transformer.scala:309`` SampleToMiniBatch, with PaddingParam support
    for variable-length sequences).

    ``drop_remainder`` defaults True for training (static shapes — a ragged
    final batch would trigger an XLA recompile; the reference instead
    right-sizes batches to the core count)."""

    def __init__(self, batch_size: int,
                 feature_padding: Optional[PaddingParam] = None,
                 label_padding: Optional[PaddingParam] = None,
                 drop_remainder: bool = True):
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.drop_remainder = drop_remainder

    def __call__(self, it):
        buf = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield batch_samples(buf, self.feature_padding,
                                    self.label_padding)
                buf = []
        if buf and not self.drop_remainder:
            yield batch_samples(buf, self.feature_padding, self.label_padding)
