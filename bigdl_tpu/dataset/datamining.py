"""Tabular rows → named tensors + feature-column ops.

Reference: ``DL/dataset/datamining/RowTransformer.scala`` (Row → Table
of named tensors through pluggable ``RowTransformSchema``s) and the
feature-column ops of ``DL/nn/ops/`` (``CategoricalColHashBucket``,
``CategoricalColVocaList``, ``CrossCol``, ``BucketizedCol``,
``IndicatorCol``).

TPU redesign: the reference runs these as forward-only "Operations"
inside the JVM graph because its executor lives where the data lives.
Under XLA, string processing cannot enter a compiled program at all —
so the whole family moves HOST-side into the data pipeline, where it
belongs: a :class:`RowTransformer` turns CSV-like rows into named numpy
columns, and the categorical ops emit :class:`~bigdl_tpu.nn.sparse.
COOBatch` batches that SparseLinear / LookupTableSparse / IndicatorCol
consume directly (id = COO column, exactly the wide-column layout
Wide&Deep wants).

Hashing note: bucket assignment uses blake2s — deterministic and
stable across runs/processes like the reference's MurmurHash3, but a
different function, so bucket IDs differ from the reference for the
same strings (semantics — stable pseudo-random distribution into
``hash_bucket_size`` buckets — are the same).  (CRC32 is NOT suitable
here: its GF(2)-linear structure makes the low bits of similar short
strings collide systematically, observed as 12 feature crosses
mapping to only 9 of 256 buckets.)
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer


def _hash_bucket(s: str, n: int) -> int:
    d = hashlib.blake2s(s.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(d, "little") % n


# ---------------------------------------------------------------- schemas
class RowTransformSchema:
    """One named extraction from a row (reference
    ``RowTransformSchema``): ``key`` names the output, ``fields``
    (names or indices) select columns, :meth:`transform` maps the
    selected values to an array."""

    def __init__(self, key: str, fields: Optional[Sequence] = None):
        self.key = key
        self.fields = list(fields) if fields is not None else None

    def transform(self, values: List) -> np.ndarray:
        raise NotImplementedError


class ColToTensor(RowTransformSchema):
    """Single column, passed through (reference ``ColToTensor``)."""

    def __init__(self, key: str, field):
        super().__init__(key, [field])

    def transform(self, values):
        return np.asarray(values[0])


class ColsToNumeric(RowTransformSchema):
    """Group of columns → one float vector (reference
    ``ColsToNumeric``)."""

    def __init__(self, key: str, fields: Sequence, dtype=np.float32):
        super().__init__(key, fields)
        self.dtype = dtype

    def transform(self, values):
        return np.asarray([float(v) for v in values], self.dtype)


class ColToSchema(RowTransformSchema):
    """Custom function schema: ``fn(values) -> array``."""

    def __init__(self, key: str, fields: Sequence, fn: Callable):
        super().__init__(key, fields)
        self.fn = fn

    def transform(self, values):
        return np.asarray(self.fn(values))


class RowTransformer(Transformer):
    """rows → dict of named arrays (reference ``RowTransformer``:
    Row → Table keyed by schema keys).

    Rows may be dicts, or tuples/lists paired with ``field_names``.
    Duplicate schema keys are rejected, like the reference."""

    def __init__(self, schemas: Sequence[RowTransformSchema],
                 field_names: Optional[Sequence[str]] = None):
        keys = [s.key for s in schemas]
        if len(set(keys)) != len(keys):
            raise ValueError(f"replicated schema keys in {keys}")
        self.schemas = list(schemas)
        self.field_names = list(field_names) if field_names else None

    @staticmethod
    def atomic(field_names: Sequence[str]) -> "RowTransformer":
        """One pass-through schema per column, keyed by column name
        (reference ``RowTransformer.atomic``)."""
        return RowTransformer([ColToTensor(f, f) for f in field_names],
                              field_names=list(field_names))

    @staticmethod
    def numeric(key: str, field_names: Sequence[str],
                all_field_names: Optional[Sequence[str]] = None
                ) -> "RowTransformer":
        """The named columns into one numeric vector (reference
        ``RowTransformer.numeric``).  ``all_field_names`` gives the
        row's full column order when it differs from the selection."""
        return RowTransformer(
            [ColsToNumeric(key, field_names)],
            field_names=list(all_field_names or field_names))

    @property
    def field_names(self):
        return self._field_names

    @field_names.setter
    def field_names(self, value):
        self._field_names = list(value) if value else None
        self._field_index = ({f: i for i, f in
                              enumerate(self._field_names)}
                             if self._field_names else None)

    def _select(self, row, fields):
        if isinstance(row, dict):
            return [row[f] for f in fields]
        if self._field_index is not None and fields and \
                isinstance(fields[0], str):
            return [row[self._field_index[f]] for f in fields]
        return [row[int(f)] for f in fields]

    def transform_row(self, row) -> Dict[str, np.ndarray]:
        out = {}
        for schema in self.schemas:
            if schema.fields is None:
                values = (list(row.values()) if isinstance(row, dict)
                          else list(row))
            else:
                values = self._select(row, schema.fields)
            out[schema.key] = schema.transform(values)
        return out

    def __call__(self, it):
        for row in it:
            yield self.transform_row(row)


# --------------------------------------------------- feature-column ops
class BucketizedCol:
    """Discretize numeric columns by boundaries (reference
    ``BucketizedCol.scala``: buckets (-inf,b0), [b0,b1), …,
    [bn,+inf))."""

    def __init__(self, boundaries: Sequence[float]):
        if len(boundaries) < 1:
            raise ValueError("need at least one boundary")
        self.boundaries = np.asarray(sorted(boundaries), np.float64)

    def __call__(self, x) -> np.ndarray:
        return np.searchsorted(self.boundaries, np.asarray(x, np.float64),
                               side="right").astype(np.int32)


def _to_coo(rows, cols, n, n_ids, vals=None):
    """Assemble a COOBatch from accumulated (row, col) id pairs; a
    NON-empty batch with no ids keeps one zero-valued placeholder entry
    so the stream stays XLA-friendly (an EMPTY batch keeps empty
    arrays — row 0 wouldn't exist)."""
    import jax.numpy as jnp
    from bigdl_tpu.nn.sparse import COOBatch
    if not rows and n > 0:
        rows, cols, vals = [0], [0], [0.0]
    elif vals is None:
        vals = [1.0] * len(rows)
    return COOBatch(jnp.asarray(np.asarray(rows, np.int32)),
                    jnp.asarray(np.asarray(cols, np.int32)),
                    jnp.asarray(np.asarray(vals, np.float32)),
                    (n, n_ids))


class _CategoricalBase:
    """Shared string → id-list machinery; subclasses map one string
    token to an id (or None to drop)."""

    def __init__(self, n_ids: int, delimiter: str = ","):
        self.n_ids = n_ids
        self.delimiter = delimiter

    def token_id(self, tok: str) -> Optional[int]:
        raise NotImplementedError

    def row_ids(self, s) -> List[int]:
        toks = [t for t in str(s).split(self.delimiter) if t != ""]
        out = []
        for t in toks:
            i = self.token_id(t)
            if i is not None:
                out.append(i)
        return out

    def __call__(self, column: Sequence):
        """batch of strings → COOBatch (row, col=id, value=1) of shape
        (N, n_ids) — directly consumable by SparseLinear /
        LookupTableSparse / IndicatorCol."""
        rows, cols = [], []
        for r, s in enumerate(column):
            for i in self.row_ids(s):
                rows.append(r)
                cols.append(i)
        return _to_coo(rows, cols, len(column), self.n_ids)


class CategoricalColHashBucket(_CategoricalBase):
    """String feature → hashed bucket ids (reference
    ``CategoricalColHashBucket.scala``; multi-value via delimiter,
    missing = empty string)."""

    def __init__(self, hash_bucket_size: int, delimiter: str = ","):
        if hash_bucket_size <= 1:
            raise ValueError("hash_bucket_size must be > 1")
        super().__init__(hash_bucket_size, delimiter)

    def token_id(self, tok):
        return _hash_bucket(tok, self.n_ids)


class CategoricalColVocaList(_CategoricalBase):
    """String feature → vocabulary ids (reference
    ``CategoricalColVocaList.scala``): OOV dropped by default, or sent
    to the default id len(vocab), or hashed into ``num_oov_buckets``
    (the two OOV modes are mutually exclusive, like the reference)."""

    def __init__(self, vocabulary: Sequence[str], delimiter: str = ",",
                 is_set_default: bool = False, num_oov_buckets: int = 0):
        if num_oov_buckets < 0:
            raise ValueError("num_oov_buckets must be >= 0")
        if num_oov_buckets and is_set_default:
            raise ValueError("num_oov_buckets cannot be combined with "
                             "is_set_default")
        self.vocab = {v: i for i, v in enumerate(vocabulary)}
        self.is_set_default = is_set_default
        self.num_oov_buckets = num_oov_buckets
        n = len(self.vocab) + (1 if is_set_default else num_oov_buckets)
        super().__init__(n, delimiter)

    def token_id(self, tok):
        if tok in self.vocab:
            return self.vocab[tok]
        if self.is_set_default:
            return len(self.vocab)
        if self.num_oov_buckets:
            return len(self.vocab) + _hash_bucket(tok,
                                                  self.num_oov_buckets)
        return None


class CrossCol:
    """Hashed cartesian product of >=2 categorical string columns
    (reference ``CrossCol.scala``): per row, every combination of the
    columns' (multi-)values hashes into one bucket id."""

    def __init__(self, hash_bucket_size: int, delimiter: str = ","):
        if hash_bucket_size <= 1:
            raise ValueError("hash_bucket_size must be > 1")
        self.n_ids = hash_bucket_size
        self.delimiter = delimiter

    def __call__(self, columns: Sequence[Sequence]):
        if len(columns) < 2:
            raise ValueError("CrossCol needs at least 2 columns")
        n = len(columns[0])
        rows, cols = [], []
        for r in range(n):
            combos = [""]
            for col in columns:
                toks = [t for t in str(col[r]).split(self.delimiter)
                        if t != ""]
                combos = [c + "\x1f" + t for c in combos for t in toks]
            for c in combos:
                rows.append(r)
                cols.append(_hash_bucket(c, self.n_ids))
        return _to_coo(rows, cols, n, self.n_ids)


class Kv2Tensor:
    """Parse "k:v" string columns into a dense matrix or COOBatch
    (reference ``nn/ops/Kv2Tensor.scala:46`` — ``transType=0`` dense,
    ``1`` sparse; key = integer column index into ``fea_len``).

    The reference runs this as a graph Operation fed a string tensor;
    strings cannot enter a jitted TPU program, so here it is a
    host-side feature column like its siblings above — same pipeline
    stage, same output contract (dense ``(N, fea_len)`` float32 or a
    ``COOBatch`` with that dense shape)."""

    def __init__(self, kv_delimiter: str = ",", item_delimiter: str = ":",
                 trans_type: int = 0):
        if trans_type not in (0, 1):
            raise ValueError("trans_type must be 0 (dense) or 1 (sparse)")
        self.kv_delimiter = kv_delimiter
        self.item_delimiter = item_delimiter
        self.trans_type = trans_type

    def __call__(self, column: Sequence, fea_len: int):
        rows, cols, vals = [], [], []
        for r, s in enumerate(column):
            for kv in str(s).split(self.kv_delimiter):
                if kv == "":
                    continue
                try:
                    k_str, v_str = kv.split(self.item_delimiter, 1)
                    k, v = int(k_str), float(v_str)
                except ValueError as e:
                    raise ValueError(
                        f"Kv2Tensor: malformed entry {kv!r} in row {r} "
                        f"({s!r}) — expected "
                        f"'<int>{self.item_delimiter}<float>'") from e
                if not 0 <= k < fea_len:
                    raise ValueError(
                        f"key {k} out of range for fea_len={fea_len}")
                rows.append(r)
                cols.append(k)
                vals.append(v)
        if self.trans_type == 0:
            out = np.zeros((len(column), fea_len), np.float32)
            # duplicate keys accumulate, matching the reference's
            # SparseTensor→dense semantics
            np.add.at(out, (rows, cols), vals)
            return out
        return _to_coo(rows, cols, len(column), fea_len, vals)


class IndicatorCol:
    """COO categorical batch → dense multi-hot/count matrix (reference
    ``IndicatorCol.scala``; ``is_count=False`` clips to 0/1)."""

    def __init__(self, fea_len: int, is_count: bool = True):
        self.fea_len = fea_len
        self.is_count = is_count

    def __call__(self, coo) -> np.ndarray:
        n = coo.n_rows
        out = np.zeros((n, self.fea_len), np.float32)
        np.add.at(out, (np.asarray(coo.row), np.asarray(coo.col)),
                  np.asarray(coo.values, np.float32))
        if not self.is_count:
            out = np.minimum(out, 1.0)
        return out
