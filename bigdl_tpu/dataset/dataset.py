"""DataSet — the training-data container.

Reference: ``DL/dataset/DataSet.scala`` — ``AbstractDataSet`` (`:57-68`:
``data(train)``, ``shuffle``, ``size``), ``LocalDataSet:113``,
``DistributedDataSet:167``, ``CachedDistriDataSet:243`` (per-partition
cached array + shuffled index array; training iterator is infinite,
sampling ``localData(indexes(i % len))``).

TPU redesign: Spark partitions → per-host shards.  ``DistributedDataSet``
shards the index space by ``jax.process_index()`` (each host holds/reads
only its shard — the analog of ``coalesce(nodeNumber)`` + locality zip),
shuffles indices host-locally each epoch exactly like the reference's
index-permutation trick (``DataSet.scala:295-302``), and the global batch
is assembled across hosts by the mesh (each host contributes its slice of
the batch via ``jax.make_array_from_process_local_data``-style sharding in
the distributed optimizer).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

import jax

from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.dataset.transformer import Transformer


class AbstractDataSet:
    def data(self, train: bool) -> Iterator:
        """Infinite shuffled iterator when train, one-pass when not
        (reference ``AbstractDataSet.data``)."""
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError

    # -- checkpoint/resume position (bigdl_tpu.checkpoint) --------------
    # The shuffle order must be reconstructible from a small JSON dict
    # for mid-epoch-exact resume: a restored run re-derives the SAME
    # permutation the interrupted run was consuming, and the driver's
    # records_processed fast-forward lands on the exact next batch.
    def position_state(self) -> dict:
        """JSON-able shuffle/stream position for a checkpoint manifest
        (empty when this dataset has no shuffle state)."""
        return {}

    def restore_position(self, state: dict) -> None:
        """Re-derive the shuffle order saved by :meth:`position_state`."""

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        return TransformedDataSet(self, transformer)

    def __rshift__(self, transformer: Transformer) -> "TransformedDataSet":
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet):
    """In-memory array dataset (reference ``LocalDataSet:113``): training
    iterator is infinite over a permuted index array; ``shuffle`` re-permutes
    indices only (data never moves)."""

    def __init__(self, data: Sequence, seed: int = 1):
        self._data = data
        self._seed = seed
        self._epoch = 0  # shuffles so far; epoch 0 = insertion order
        self._indexes = np.arange(len(data))

    def size(self) -> int:
        return len(self._data)

    def _permutation(self, epoch: int) -> np.ndarray:
        # epoch-KEYED permutation (not a sequentially-advanced rng): the
        # order of epoch E is a pure function of (seed, E), so a resumed
        # run re-derives it without replaying E-1 earlier shuffles —
        # the mid-epoch-exact-resume contract of bigdl_tpu.checkpoint
        if epoch == 0:
            return np.arange(len(self._data))
        return np.random.default_rng(
            (self._seed, epoch)).permutation(len(self._data))

    def shuffle(self) -> None:
        self._epoch += 1
        self._indexes = self._permutation(self._epoch)

    def position_state(self) -> dict:
        return {"shuffle_epoch": self._epoch}

    def restore_position(self, state: dict) -> None:
        self._epoch = int(state.get("shuffle_epoch", 0))
        self._indexes = self._permutation(self._epoch)

    def data(self, train: bool) -> Iterator:
        if train:
            def infinite():
                i = 0
                n = len(self._data)
                while True:
                    yield self._data[self._indexes[i % n]]
                    i += 1
            return infinite()
        return iter(self._data)


class DistributedDataSet(AbstractDataSet):
    """Per-host sharded dataset.  Host p of P sees indices p::P — the analog
    of the reference's ``coalesce(nodeNumber, true)`` partition placement
    (``DataSet.scala:340-344``).  All hosts permute with the same seed so
    epoch boundaries stay aligned (SPMD requires lock-step batch counts)."""

    def __init__(self, data: Sequence, seed: int = 1,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        self._data = data
        self._p = jax.process_index() if process_index is None else process_index
        self._np = jax.process_count() if process_count is None else process_count
        self._seed = seed
        self._epoch = 0
        self._global_indexes = np.arange(len(data))

    def size(self) -> int:
        """GLOBAL size (reference DistributedDataSet.size is the RDD count)."""
        return len(self._data)

    def local_size(self) -> int:
        return len(range(self._p, len(self._data), self._np))

    def _permutation(self) -> np.ndarray:
        if self._epoch == 0:
            return np.arange(len(self._data))
        return np.random.default_rng(
            self._seed + self._epoch).permutation(len(self._data))

    def shuffle(self) -> None:
        self._epoch += 1
        self._global_indexes = self._permutation()

    def position_state(self) -> dict:
        return {"shuffle_epoch": self._epoch}

    def restore_position(self, state: dict) -> None:
        # already epoch-keyed (all hosts permute with the same seed) —
        # restoring is just re-deriving the permutation for that epoch
        self._epoch = int(state.get("shuffle_epoch", 0))
        self._global_indexes = self._permutation()

    def data(self, train: bool) -> Iterator:
        local = self._global_indexes[self._p::self._np]
        if train:
            def infinite():
                i = 0
                while True:
                    # re-read shard each wrap so shuffle() takes effect
                    cur = self._global_indexes[self._p::self._np]
                    yield self._data[cur[i % len(cur)]]
                    i += 1
            return infinite()
        return (self._data[i] for i in local)


class TransformedDataSet(AbstractDataSet):
    """DataSet with a transformer pipeline attached (reference: the result
    of ``dataset -> transformer``)."""

    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self) -> int:
        return self.base.size()

    def shuffle(self) -> None:
        self.base.shuffle()

    def position_state(self) -> dict:
        fn = getattr(self.base, "position_state", None)
        return fn() if fn is not None else {}

    def restore_position(self, state: dict) -> None:
        fn = getattr(self.base, "restore_position", None)
        if fn is not None:
            fn(state)

    def data(self, train: bool) -> Iterator:
        return self.transformer(self.base.data(train))

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        return TransformedDataSet(self.base, self.transformer >> transformer)


class DataSet:
    """Factory namespace (reference ``DataSet.array/rdd/imageFrame``,
    ``DataSet.scala:322+``)."""

    @staticmethod
    def array(data: Sequence, distributed: bool = False,
              seed: int = 1) -> AbstractDataSet:
        if distributed:
            return DistributedDataSet(data, seed=seed)
        return LocalDataSet(data, seed=seed)
