"""MovieLens ratings loader (NCF / Wide&Deep workloads).

Reference: ``pyspark/bigdl/dataset/movielens.py`` — parses the
``ml-1m/ratings.dat`` ``user::item::rating::timestamp`` format.  No
downloading here (zero-egress environments); point ``load`` at an
extracted tree or use :func:`synthetic_ratings`.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np


def load(folder: str, filename: str = "ratings.dat") -> np.ndarray:
    """Return an int array (N, 3) of [user, item, rating] (1-based ids,
    like the reference's parser)."""
    path = os.path.join(folder, filename)
    out = []
    with open(path) as f:
        for line in f:
            parts = line.strip().split("::")
            if len(parts) >= 3:
                out.append((int(parts[0]), int(parts[1]),
                            int(float(parts[2]))))
    return np.asarray(out, np.int32)


def synthetic_ratings(n_users: int = 200, n_items: int = 100,
                      n_ratings: int = 5000, seed: int = 0) -> np.ndarray:
    """Deterministic synthetic ratings with real structure: each user has
    a latent preference vector, so NCF-style models can actually fit."""
    rng = np.random.default_rng(seed)
    u_lat = rng.normal(0, 1, (n_users, 4))
    i_lat = rng.normal(0, 1, (n_items, 4))
    users = rng.integers(0, n_users, n_ratings)
    items = rng.integers(0, n_items, n_ratings)
    score = (u_lat[users] * i_lat[items]).sum(1)
    rating = np.clip(np.round(3 + score), 1, 5).astype(np.int32)
    return np.stack([users + 1, items + 1, rating], axis=1).astype(np.int32)


def to_implicit_samples(ratings: np.ndarray, threshold: int = 4):
    """[user, item, rating] → Samples of ((user, item), clicked) for the
    NCF binary objective (reference NCF example preprocessing)."""
    from bigdl_tpu.dataset.sample import Sample
    return [Sample(np.asarray([r[0] - 1, r[1] - 1], np.int32),
                   np.int32(1 if r[2] >= threshold else 0))
            for r in ratings]
