"""20 Newsgroups loader (text-classification workloads).

Reference: ``pyspark/bigdl/dataset/news20.py`` — walks the extracted
``20news-18828`` tree where each subdirectory is a category of text
files.  No downloading (zero-egress); use :func:`synthetic_news` without
the real corpus.
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np


def load(folder: str) -> Tuple[List[str], np.ndarray, List[str]]:
    """Walk ``folder/<category>/<doc>`` → (texts, labels, category names),
    categories sorted for stable label ids."""
    categories = sorted(d for d in os.listdir(folder)
                        if os.path.isdir(os.path.join(folder, d)))
    texts: List[str] = []
    labels: List[int] = []
    for ix, cat in enumerate(categories):
        cdir = os.path.join(folder, cat)
        for doc in sorted(os.listdir(cdir)):
            with open(os.path.join(cdir, doc), "rb") as f:
                texts.append(f.read().decode("latin-1"))
            labels.append(ix)
    return texts, np.asarray(labels, np.int32), categories


def synthetic_news(n_docs: int = 400, n_classes: int = 4, seed: int = 0
                   ) -> Tuple[List[str], np.ndarray, List[str]]:
    """Class-specific vocabularies + shared filler words, deterministic."""
    rng = np.random.default_rng(seed)
    cats = [f"topic{i}" for i in range(n_classes)]
    vocab = {c: [f"{c}_w{j}" for j in range(30)] for c in cats}
    shared = [f"common{j}" for j in range(30)]
    texts, labels = [], []
    for _ in range(n_docs):
        y = int(rng.integers(0, n_classes))
        n = int(rng.integers(20, 60))
        words = rng.choice(vocab[cats[y]] + shared, size=n)
        texts.append(" ".join(words))
        labels.append(y)
    return texts, np.asarray(labels, np.int32), cats
