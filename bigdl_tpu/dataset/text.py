"""Text data pipeline: Dictionary, tokenizers, sentence transformers.

Reference: ``DL/dataset/text/`` (8 files) — ``Dictionary.scala`` (vocab
with index maps, ``padding``/``unknown`` discovery), ``SentenceTokenizer``
(OpenNLP), ``SentenceSplitter``, ``TextToLabeledSentence``,
``LabeledSentenceToSample``, ``LabeledSentence``; plus the PTB loading in
``DL/example/languagemodel/PTBWordLM.scala`` and
``DL/models/rnn/Train.scala``.

TPU redesign: OpenNLP's JNI tokenizer becomes a small regex tokenizer
(identical role, no native dep); everything else is a direct functional
analog.  Fixed-length padding/truncation happens here (host-side) so the
jit'd step sees one static shape — the bucketing answer to the
"PaddingParam must avoid recompilation storms" risk (SURVEY §7).
"""

from __future__ import annotations

import os
import re
from collections import Counter
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer

SENTENCE_START = "SENTENCE_START"
SENTENCE_END = "SENTENCE_END"


def sentence_splitter(text: str) -> List[str]:
    """Split running text into sentences (reference ``SentenceSplitter``,
    OpenNLP model → punctuation heuristic)."""
    parts = re.split(r"(?<=[.!?])\s+", text.strip())
    return [p for p in parts if p]


def sentence_tokenizer(sentence: str) -> List[str]:
    """Tokenize one sentence (reference ``SentenceTokenizer``): words,
    numbers, or single punctuation marks."""
    return re.findall(r"[\w']+|[^\w\s]", sentence.lower())


class SentenceTokenizer(Transformer):
    """str → List[str] transformer form."""

    def __call__(self, it: Iterator[str]) -> Iterator[List[str]]:
        return (sentence_tokenizer(s) for s in it)


class SentenceBiPadding(Transformer):
    """Add SENTENCE_START/SENTENCE_END markers (reference
    ``SentenceBiPadding.scala``)."""

    def __call__(self, it):
        for toks in it:
            yield [SENTENCE_START] + list(toks) + [SENTENCE_END]


class Dictionary:
    """Vocabulary (reference ``Dictionary.scala``): word↔index maps over
    the ``vocab_size`` most frequent words, everything else mapped to an
    unknown token appended at the end."""

    UNKNOWN = "<unk>"

    def __init__(self, sentences: Optional[Iterable[Sequence[str]]] = None,
                 vocab_size: Optional[int] = None):
        self.word2index: Dict[str, int] = {}
        self.index2word: List[str] = []
        if sentences is not None:
            counts = Counter(w for s in sentences for w in s)
            keep = counts.most_common(vocab_size)
            for w, _ in keep:
                self.word2index[w] = len(self.index2word)
                self.index2word.append(w)
            if self.UNKNOWN not in self.word2index:
                self.word2index[self.UNKNOWN] = len(self.index2word)
                self.index2word.append(self.UNKNOWN)

    def vocab_size(self) -> int:
        return len(self.index2word)

    def index(self, word: str) -> int:
        return self.word2index.get(word, self.word2index[self.UNKNOWN])

    def word(self, ix: int) -> str:
        return self.index2word[ix]

    def encode(self, tokens: Sequence[str]) -> np.ndarray:
        return np.asarray([self.index(w) for w in tokens], np.int32)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for w in self.index2word:
                f.write(w + "\n")

    @staticmethod
    def load(path: str) -> "Dictionary":
        d = Dictionary()
        with open(path) as f:
            for line in f:
                w = line.rstrip("\n")
                d.word2index[w] = len(d.index2word)
                d.index2word.append(w)
        return d


class LabeledSentence:
    """(data indices, label indices) pair (reference
    ``LabeledSentence.scala``)."""

    __slots__ = ("data", "label")

    def __init__(self, data: np.ndarray, label: np.ndarray):
        self.data = np.asarray(data, np.int32)
        self.label = np.asarray(label, np.int32)


class TextToLabeledSentence(Transformer):
    """Language-model shift: data = tokens[:-1], label = tokens[1:]
    (reference ``TextToLabeledSentence.scala``)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def __call__(self, it):
        for toks in it:
            ids = self.dictionary.encode(toks)
            if len(ids) < 2:
                continue
            yield LabeledSentence(ids[:-1], ids[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence → fixed-length Sample (reference
    ``LabeledSentenceToSample.scala``).  Pads/truncates to
    ``fixed_length`` with ``padding_value`` so the jit'd step sees ONE
    static shape."""

    def __init__(self, fixed_length: int, padding_value: int = 0,
                 one_hot: bool = False, vocab_size: Optional[int] = None):
        self.fixed_length = fixed_length
        self.padding_value = padding_value
        self.one_hot = one_hot
        self.vocab_size = vocab_size

    def _fix(self, ids: np.ndarray) -> np.ndarray:
        L = self.fixed_length
        if len(ids) >= L:
            return ids[:L]
        pad = np.full(L - len(ids), self.padding_value, np.int32)
        return np.concatenate([ids, pad])

    def __call__(self, it):
        for ls in it:
            data = self._fix(ls.data)
            label = self._fix(ls.label)
            if self.one_hot:
                eye = np.eye(self.vocab_size, dtype=np.float32)
                data = eye[data]
            yield Sample(data, label)


# --------------------------------------------------------------- PTB corpus
def read_ptb_words(path: str) -> List[str]:
    """Read a PTB-format file into a flat word stream with <eos> sentence
    ends (reference ``PTBWordLM`` reading convention)."""
    words: List[str] = []
    with open(path) as f:
        for line in f:
            words.extend(line.split())
            words.append("<eos>")
    return words


def ptb_batches(word_ids: np.ndarray, num_steps: int
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Contiguous (data, label) windows of ``num_steps`` (reference
    ``PTBModel`` input prep): label is data shifted by one."""
    n = (len(word_ids) - 1) // num_steps
    x = word_ids[:n * num_steps].reshape(n, num_steps)
    y = word_ids[1:n * num_steps + 1].reshape(n, num_steps)
    return x, y


def synthetic_corpus(n_sentences: int = 200, seed: int = 0) -> List[str]:
    """Deterministic synthetic corpus with Zipf-ish word frequencies, for
    examples/tests without the real PTB files."""
    rng = np.random.default_rng(seed)
    vocab = [f"w{i}" for i in range(50)]
    probs = 1.0 / np.arange(1, 51)
    probs /= probs.sum()
    out = []
    for _ in range(n_sentences):
        n = int(rng.integers(4, 12))
        out.append(" ".join(rng.choice(vocab, size=n, p=probs)) + " .")
    return out
