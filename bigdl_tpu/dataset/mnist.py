"""MNIST idx-format loader (reference: ``DL/models/lenet/Utils.scala``
``load`` reads idx ubyte files; ``pyspark/bigdl/dataset/mnist.py`` mirrors).

No network access is assumed: ``load_mnist`` reads local idx files;
``synthetic_mnist`` generates a deterministic MNIST-shaped classification
set (class-conditional blob patterns) for tests/demos.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from bigdl_tpu.dataset.sample import Sample

TRAIN_MEAN = 0.13066047740239506 * 255
TRAIN_STD = 0.3081078 * 255
TEST_MEAN = 0.13251460696903547 * 255
TEST_STD = 0.31048024 * 255


def _open(path):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def read_idx_images(path: str) -> np.ndarray:
    with _open(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad image idx magic {magic}"
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def read_idx_labels(path: str) -> np.ndarray:
    with _open(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad label idx magic {magic}"
        return np.frombuffer(f.read(n), dtype=np.uint8).astype(np.int32)


def load_mnist(folder: str, train: bool = True):
    """Return (images uint8 (N,28,28), labels int32 (N,)).  Accepts the
    standard file names, gzipped or not."""
    prefix = "train" if train else "t10k"
    img, lbl = None, None
    for suff in ("-images-idx3-ubyte", "-images.idx3-ubyte"):
        for ext in ("", ".gz"):
            p = os.path.join(folder, prefix + suff + ext)
            if os.path.exists(p):
                img = read_idx_images(p)
    for suff in ("-labels-idx1-ubyte", "-labels.idx1-ubyte"):
        for ext in ("", ".gz"):
            p = os.path.join(folder, prefix + suff + ext)
            if os.path.exists(p):
                lbl = read_idx_labels(p)
    if img is None or lbl is None:
        raise FileNotFoundError(f"no MNIST idx files under {folder}")
    return img, lbl


def synthetic_mnist(n: int = 2048, n_classes: int = 10, seed: int = 0,
                    size: int = 28, template_seed: int = 1234):
    """Deterministic MNIST-shaped synthetic data: each class is a distinct
    smoothed random template plus noise.  Learnable to >99% by LeNet —
    used by tests and demos in place of the real download.

    ``template_seed`` fixes the class templates (the "digit shapes") so
    different ``seed`` values yield train/val splits of the SAME task."""
    rng = np.random.default_rng(seed)
    templates = np.random.default_rng(template_seed).normal(
        0, 1, (n_classes, size, size))
    # smooth templates so conv nets have local structure to find
    k = np.ones((5, 5)) / 25.0
    for c in range(n_classes):
        t = templates[c]
        padded = np.pad(t, 2, mode="edge")
        sm = np.zeros_like(t)
        for i in range(size):
            for j in range(size):
                sm[i, j] = np.sum(padded[i:i + 5, j:j + 5] * k)
        templates[c] = sm
    templates = (templates - templates.min()) / np.ptp(templates) * 200
    labels = rng.integers(0, n_classes, n).astype(np.int32)
    images = templates[labels] + rng.normal(0, 20, (n, size, size))
    images = np.clip(images, 0, 255).astype(np.uint8)
    return images, labels


def to_samples(images: np.ndarray, labels: np.ndarray):
    return [Sample(images[i], labels[i]) for i in range(len(labels))]
