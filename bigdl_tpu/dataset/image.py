"""Image preprocessing transformers (host-side, numpy).

Reference: ``DL/dataset/image/`` (23 files: ``BytesToGreyImg``,
``GreyImgNormalizer``, ``BGRImgCropper``, ``ColorJitter``, ``Lighting``,
``HFlip``, …) and the vision-2.0 augmentation ops under
``DL/transform/vision/image/augmentation/``.  The reference does this with
JNI OpenCV; here it is pure numpy on the host CPU — augmentation happens
before ``device_put``, never on the TPU.

Greyscale images flow as float32 (H, W); BGR/RGB images as float32 (H, W, C).
Each transformer maps Sample→Sample so pipelines read like the reference:
``dataset >> GreyImgNormalizer(mean, std) >> GreyImgToSample() >> SampleToMiniBatch(b)``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.imgops import ThreadRng, color_jitter, lighting_delta


class _SampleMap(Transformer):
    def _map(self, s: Sample) -> Sample:
        raise NotImplementedError

    def __call__(self, it):
        return (self._map(s) for s in it)


class BytesToGreyImg(_SampleMap):
    """uint8 (H,W) → float32 (reference ``BytesToGreyImg``)."""

    def _map(self, s):
        return Sample(s.feature.astype(np.float32), s.label)


class GreyImgNormalizer(_SampleMap):
    """(x - mean) / std (reference ``GreyImgNormalizer``)."""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = mean, std

    def _map(self, s):
        f = (s.feature.astype(np.float32) - self.mean) / self.std
        return Sample(f, s.label)


class GreyImgToSample(_SampleMap):
    """Add the channel dim: (H,W) → (1,H,W) (reference ``GreyImgToBatch``
    does this while batching; batching itself is SampleToMiniBatch here)."""

    def _map(self, s):
        return Sample(s.feature[None, :, :].astype(np.float32), s.label)


class BGRImgNormalizer(_SampleMap):
    """Per-channel (x-mean)/std on (H,W,C) (reference ``BGRImgNormalizer``)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def _map(self, s):
        return Sample((s.feature - self.mean) / self.std, s.label)


class HFlip(_SampleMap):
    """Random horizontal flip (reference ``HFlip``)."""

    def __init__(self, threshold: float = 0.5, seed: int = 0):
        self.threshold = threshold
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def _map(self, s):
        if self._rng.random() < self.threshold:
            return Sample(np.ascontiguousarray(s.feature[:, ::-1]), s.label)
        return s


class RandomCropper(_SampleMap):
    """Random crop to (h, w), optionally after padding (reference
    ``BGRImgCropper``/``RandomCropper``; the CIFAR recipe pads 4 then crops
    32)."""

    def __init__(self, crop_h: int, crop_w: int, pad: int = 0, seed: int = 0):
        self.crop_h, self.crop_w, self.pad = crop_h, crop_w, pad
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def _map(self, s):
        f = s.feature
        chw = f.ndim == 3 and f.shape[0] <= 4  # (C,H,W) vs (H,W[,C])
        if self.pad:
            if chw:
                f = np.pad(f, ((0, 0), (self.pad, self.pad),
                               (self.pad, self.pad)))
            elif f.ndim == 3:
                f = np.pad(f, ((self.pad, self.pad), (self.pad, self.pad),
                               (0, 0)))
            else:
                f = np.pad(f, self.pad)
        H, W = (f.shape[1], f.shape[2]) if chw else (f.shape[0], f.shape[1])
        y = self._rng.integers(0, H - self.crop_h + 1)
        x = self._rng.integers(0, W - self.crop_w + 1)
        if chw:
            out = f[:, y:y + self.crop_h, x:x + self.crop_w]
        else:
            out = f[y:y + self.crop_h, x:x + self.crop_w]
        return Sample(np.ascontiguousarray(out), s.label)


class CenterCropper(_SampleMap):
    def __init__(self, crop_h: int, crop_w: int):
        self.crop_h, self.crop_w = crop_h, crop_w

    def _map(self, s):
        f = s.feature
        chw = f.ndim == 3 and f.shape[0] <= 4
        H, W = (f.shape[1], f.shape[2]) if chw else (f.shape[0], f.shape[1])
        y, x = (H - self.crop_h) // 2, (W - self.crop_w) // 2
        out = f[:, y:y + self.crop_h, x:x + self.crop_w] if chw \
            else f[y:y + self.crop_h, x:x + self.crop_w]
        return Sample(np.ascontiguousarray(out), s.label)


class ColorJitter(_SampleMap):
    """Random brightness/contrast/saturation on (H,W,C) float images
    (reference ``ColorJitter``)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, seed: int = 0):
        self.b, self.c, self.s = brightness, contrast, saturation
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def _map(self, s):
        return Sample(color_jitter(s.feature.astype(np.float32), self._rng,
                                   self.b, self.c, self.s), s.label)


class Lighting(_SampleMap):
    """AlexNet-style PCA lighting noise (reference ``Lighting``; the
    ImageNet eigen constants live in ``utils/imgops``)."""

    def __init__(self, alphastd: float = 0.1, seed: int = 0):
        self.alphastd = alphastd
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def _map(self, s):
        return Sample(s.feature + lighting_delta(self._rng, self.alphastd),
                      s.label)


class ChannelOrder(_SampleMap):
    """HWC→CHW (or back) (the reference stores BGR HWC and transposes when
    batching)."""

    def __init__(self, to: str = "CHW"):
        self.to = to

    def _map(self, s):
        f = s.feature
        if self.to == "CHW" and f.ndim == 3:
            return Sample(np.ascontiguousarray(f.transpose(2, 0, 1)), s.label)
        if self.to == "HWC" and f.ndim == 3:
            return Sample(np.ascontiguousarray(f.transpose(1, 2, 0)), s.label)
        return s
