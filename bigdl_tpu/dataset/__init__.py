"""(populated in subsequent milestones)"""
