"""bigdl_tpu.dataset — data pipeline (reference ``DL/dataset/`` +
``DL/transform/vision/``)."""

from bigdl_tpu.dataset.sample import (
    Sample, MiniBatch, PaddingParam, SparseSample, SparseMiniBatch,
    batch_samples, batch_sparse_samples,
)
from bigdl_tpu.dataset.transformer import (
    Transformer, ChainedTransformer, FnTransformer, SampleToMiniBatch,
)
from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, LocalDataSet, DistributedDataSet, TransformedDataSet,
    DataSet,
)
from bigdl_tpu.dataset import image
from bigdl_tpu.dataset import mnist
from bigdl_tpu.dataset import cifar
from bigdl_tpu.dataset import text
from bigdl_tpu.dataset import tfrecord
from bigdl_tpu.dataset import seqfile
from bigdl_tpu.dataset import movielens
from bigdl_tpu.dataset import news20
from bigdl_tpu.dataset.prefetch import MTSampleToMiniBatch
from bigdl_tpu.dataset.datamining import (
    BucketizedCol, CategoricalColHashBucket, CategoricalColVocaList,
    ColToSchema, ColToTensor, ColsToNumeric, CrossCol, IndicatorCol,
    Kv2Tensor, RowTransformer, RowTransformSchema,
)
