"""TFRecord file reading/writing + tf.Example (de)serialization.

Reference: ``DL/utils/tf/TFRecordIterator`` (record framing reader),
``TFRecordInputFormat``, and the ``ParsingOps`` in ``DL/nn/tf/`` that
decode ``tf.train.Example`` protos.  The *writer* side of the framing
already exists for TensorBoard events (``utils/summary.py``); this module
adds the general-purpose reader and a schema-light Example codec built on
``utils/protowire`` — no generated protobuf code (SURVEY §2.8: the
reference carries 187k LoC of generated Java for this).

tf.train.Example schema (field numbers from tensorflow/core/example):
    Example{1: Features}; Features{1: map<string, Feature>} where the map
    entry is {1: key, 2: Feature}; Feature{1: BytesList, 2: FloatList,
    3: Int64List}; each list is {1: repeated value}.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterator, List, Optional, Union

import numpy as np

from bigdl_tpu.utils import protowire as pw
from bigdl_tpu.utils.summary import _masked_crc


# ------------------------------------------------------------ record frame
def read_records(path: str, verify_crc: bool = True) -> Iterator[bytes]:
    """Iterate raw record payloads of a TFRecord file (reference
    ``TFRecordIterator``).  Framing: u64-le length, u32 masked-crc(length),
    payload, u32 masked-crc(payload)."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            (len_crc,) = struct.unpack("<I", f.read(4))
            if verify_crc and _masked_crc(header) != len_crc:
                raise IOError(f"corrupt TFRecord length crc in {path}")
            payload = f.read(length)
            if len(payload) < length:
                raise IOError(f"truncated TFRecord in {path}")
            (data_crc,) = struct.unpack("<I", f.read(4))
            if verify_crc and _masked_crc(payload) != data_crc:
                raise IOError(f"corrupt TFRecord data crc in {path}")
            yield payload


def write_records(path: str, payloads) -> None:
    """Write raw payloads in TFRecord framing (mirror of
    ``summary.FileWriter._write_record``)."""
    with open(path, "wb") as f:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))


# ------------------------------------------------------------- tf.Example
FeatureValue = Union[bytes, str, float, int, List, np.ndarray]


def encode_example(features: Dict[str, FeatureValue]) -> bytes:
    """Build a serialized tf.train.Example from a {name: value} dict.
    bytes/str → BytesList, float(array) → FloatList, int(array) → Int64List."""
    entries = b""
    for key, value in features.items():
        if isinstance(value, (bytes, str)):
            vals = [value.encode() if isinstance(value, str) else value]
            inner = b"".join(pw.enc_bytes(1, v) for v in vals)
            feat = pw.enc_bytes(1, inner)                    # BytesList
        else:
            arr = np.asarray(value)
            if np.issubdtype(arr.dtype, np.floating):
                inner = pw.enc_bytes(
                    1, struct.pack(f"<{arr.size}f",
                                   *arr.reshape(-1).astype(np.float32)))
                feat = pw.enc_bytes(2, inner)                # FloatList
            else:
                inner = b"".join(pw.varint(int(v))
                                 for v in arr.reshape(-1))
                feat = pw.enc_bytes(3, pw.enc_bytes(1, inner))  # Int64List
        entry = pw.enc_str(1, key) + pw.enc_bytes(2, feat)
        entries += pw.enc_bytes(1, entry)
    return pw.enc_bytes(1, entries)  # Example{1: Features}


def decode_example(data: bytes) -> Dict[str, Union[List[bytes], np.ndarray]]:
    """Parse a serialized tf.train.Example into {name: values}.
    BytesList → list[bytes]; FloatList → float32 ndarray;
    Int64List → int64 ndarray."""
    example = pw.decode_message(data)
    out: Dict[str, Union[List[bytes], np.ndarray]] = {}
    for features_bytes in example.get(1, []):
        features = pw.decode_message(features_bytes)
        for entry_bytes in features.get(1, []):
            entry = pw.decode_message(entry_bytes)
            key = pw.as_str(entry[1][0])
            feature = pw.decode_message(entry[2][0])
            if 1 in feature:     # BytesList
                bl = pw.decode_message(feature[1][0])
                out[key] = list(bl.get(1, []))
            elif 2 in feature:   # FloatList (packed or not)
                fl = pw.decode_message(feature[2][0])
                vals: List[float] = []
                for v in fl.get(1, []):
                    if isinstance(v, bytes):
                        vals.extend(pw.unpack_packed(v, "float"))
                    else:
                        vals.append(pw.as_float(v))
                out[key] = np.asarray(vals, np.float32)
            elif 3 in feature:   # Int64List
                il = pw.decode_message(feature[3][0])
                vals = []
                for v in il.get(1, []):
                    if isinstance(v, bytes):
                        vals.extend(pw.as_sint(x) for x in
                                    pw.unpack_packed(v, "varint"))
                    else:
                        vals.append(pw.as_sint(v))
                out[key] = np.asarray(vals, np.int64)
            else:
                out[key] = []
    return out


def read_examples(path: str) -> Iterator[Dict]:
    """Iterate decoded tf.Examples from a TFRecord file."""
    for payload in read_records(path):
        yield decode_example(payload)


def write_examples(path: str, feature_dicts) -> None:
    write_records(path, (encode_example(d) for d in feature_dicts))
