"""CIFAR-10/100 loaders.

Reference: ``DL/models/resnet/DataSet.scala`` + ``models/vgg/Train.scala``
load CIFAR-10 from the python-pickle batches or binary records; the
recipes normalize with the per-channel train statistics below
(``DL/models/resnet/DataSet.scala`` trainMean/trainStd) and augment with
pad-4 random crop + horizontal flip.

Supports both on-disk formats: the ``cifar-10-batches-bin`` binary records
(1 label byte + 3072 RGB bytes) and the ``cifar-10-batches-py`` pickles.
``synthetic_cifar`` mirrors ``mnist.synthetic_mnist`` so every example and
test runs without the real dataset.
"""

from __future__ import annotations

import gzip
import os
import pickle
import tarfile
from typing import List, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample

# per-channel RGB stats of the CIFAR-10 train split, in [0, 255] scale
# (reference ``models/resnet/DataSet.scala`` trainMean = (0.4914, 0.4822,
# 0.4465), trainStd = (0.2470, 0.2435, 0.2616) on [0,1])
TRAIN_MEAN = (125.31, 122.95, 113.87)
TRAIN_STD = (62.99, 62.09, 66.70)


def _load_bin_file(path: str) -> Tuple[np.ndarray, np.ndarray]:
    raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
    labels = raw[:, 0].astype(np.int32)
    images = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return images, labels  # (N, 32, 32, 3) uint8 RGB, (N,)


def _load_py_batch(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    images = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    labels = np.asarray(d.get(b"labels", d.get(b"fine_labels")), np.int32)
    return images, labels


def load_cifar10(folder: str, train: bool = True
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Load CIFAR-10 from ``folder`` holding either the binary batches
    (``data_batch_1.bin``…) or python batches (``data_batch_1``…).
    Returns (images (N,32,32,3) uint8 RGB, labels int32)."""
    bin_names = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                 if train else ["test_batch.bin"])
    py_names = ([f"data_batch_{i}" for i in range(1, 6)]
                if train else ["test_batch"])
    for names, loader in ((bin_names, _load_bin_file),
                          (py_names, _load_py_batch)):
        paths = [os.path.join(folder, n) for n in names]
        # also look inside the conventional extracted dirs
        for sub in ("cifar-10-batches-bin", "cifar-10-batches-py"):
            alt = [os.path.join(folder, sub, n) for n in names]
            if all(os.path.exists(p) for p in alt):
                paths = alt
        if all(os.path.exists(p) for p in paths):
            parts = [loader(p) for p in paths]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
    raise FileNotFoundError(f"no CIFAR-10 batches under {folder}")


def synthetic_cifar(n: int = 2048, n_classes: int = 10, seed: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic CIFAR-shaped synthetic data: class-dependent colored
    blobs so models can actually fit it (same idea as
    ``mnist.synthetic_mnist``)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, n).astype(np.int32)
    images = rng.integers(0, 40, (n, 32, 32, 3)).astype(np.float32)
    # class signature: a bright square whose position/channel depends on y
    for i, y in enumerate(labels):
        r, c = divmod(int(y), 4)
        images[i, r * 8:(r + 1) * 8, c * 8:(c + 1) * 8, y % 3] += 180.0
    return images.astype(np.uint8), labels


def to_samples(images: np.ndarray, labels: np.ndarray) -> List[Sample]:
    """uint8 HWC images + int labels → Samples with float32 features."""
    return [Sample(images[i].astype(np.float32), np.int32(labels[i]))
            for i in range(len(images))]
