"""Sample and MiniBatch.

Reference: ``DL/dataset/Sample.scala:32`` (features+label ndarrays, flat
storage) and ``DL/dataset/MiniBatch.scala:34`` (``ArrayTensorMiniBatch``
with ``slice`` for per-thread sub-batching).

Host-side data is numpy (cheap mutation, no device churn); a MiniBatch's
arrays move to device HBM when the jit'd step consumes them.  ``slice``
is kept for parity/sub-batching; per-core sub-batching itself is obsolete
under SPMD (the mesh shards the batch instead).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np


class Sample:
    """One training example: feature array(s) + label array(s)."""

    __slots__ = ("feature", "label")

    def __init__(self, feature, label=None):
        self.feature = feature
        self.label = label

    @staticmethod
    def from_ndarray(feature, label=None) -> "Sample":
        f = np.asarray(feature)
        l = None if label is None else np.asarray(label)
        return Sample(f, l)

    def feature_size(self):
        return self.feature.shape

    def label_size(self):
        return None if self.label is None else self.label.shape

    def __repr__(self):
        ls = None if self.label is None else self.label.shape
        return f"Sample(feature={self.feature.shape}, label={ls})"


class MiniBatch:
    """Batched input/target pair (pytrees of arrays with leading batch dim)."""

    __slots__ = ("input", "target")

    def __init__(self, input, target=None):
        self.input = input
        self.target = target

    def size(self) -> int:
        leaf = self.input
        while isinstance(leaf, (tuple, list, dict)):
            leaf = next(iter(leaf.values())) if isinstance(leaf, dict) \
                else leaf[0]
        return leaf.shape[0]

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """Sub-batch [offset, offset+length) (reference
        ``MiniBatch.scala:155``)."""

        def cut(x):
            if isinstance(x, dict):
                return {k: cut(v) for k, v in x.items()}
            if isinstance(x, (tuple, list)):
                return type(x)(cut(e) for e in x)
            return x[offset:offset + length]

        return MiniBatch(cut(self.input),
                         None if self.target is None else cut(self.target))

    def __repr__(self):
        return f"MiniBatch(size={self.size()})"


@dataclass
class PaddingParam:
    """Variable-length padding config (reference ``Transformer.scala``
    PaddingParam): pad every sequence in the batch to the longest (or to
    ``fixed_length``) with ``padding_value``.

    ``buckets``: pad to the smallest listed length >= the batch's
    natural max instead — under XLA each distinct padded length is a
    separate compile, so bucketing bounds the compile count to
    ``len(buckets)`` (the SURVEY §7 "recompilation storms" mitigation;
    the reference pads per-batch because the JVM has no such cost)."""

    padding_value: float = 0.0
    fixed_length: Optional[int] = None
    buckets: Optional[Sequence[int]] = None


def _stack_padded(arrays: Sequence[np.ndarray], param: Optional[PaddingParam]):
    """Stack arrays; if ragged in dim 0 (sequence), pad per PaddingParam."""
    shapes = {a.shape for a in arrays}
    if len(shapes) == 1 and param is None:
        return np.stack(arrays)
    if param is None:
        raise ValueError(
            f"ragged samples {sorted(shapes)} need a PaddingParam")
    max_len = param.fixed_length or max(a.shape[0] for a in arrays)
    if param.buckets is not None and param.fixed_length is None:
        fitting = [b for b in sorted(param.buckets) if b >= max_len]
        if not fitting:
            raise ValueError(
                f"sequence length {max_len} exceeds the largest bucket "
                f"{max(param.buckets)}")
        max_len = fitting[0]
    out_shape = (len(arrays), max_len) + arrays[0].shape[1:]
    out = np.full(out_shape, param.padding_value, dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        out[i, :a.shape[0]] = a
    return out


def batch_samples(samples: Sequence[Sample],
                  feature_padding: Optional[PaddingParam] = None,
                  label_padding: Optional[PaddingParam] = None) -> MiniBatch:
    """Collate samples into a MiniBatch (reference ``SampleToMiniBatch``
    internals)."""
    feats = _stack_padded([s.feature for s in samples], feature_padding)
    if samples[0].label is None:
        return MiniBatch(feats, None)
    labels = _stack_padded([np.asarray(s.label) for s in samples],
                           label_padding)
    return MiniBatch(feats, labels)
