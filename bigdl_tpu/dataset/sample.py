"""Sample and MiniBatch.

Reference: ``DL/dataset/Sample.scala:32`` (features+label ndarrays, flat
storage) and ``DL/dataset/MiniBatch.scala:34`` (``ArrayTensorMiniBatch``
with ``slice`` for per-thread sub-batching).

Host-side data is numpy (cheap mutation, no device churn); a MiniBatch's
arrays move to device HBM when the jit'd step consumes them.  ``slice``
is kept for parity/sub-batching; per-core sub-batching itself is obsolete
under SPMD (the mesh shards the batch instead).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np


class Sample:
    """One training example: feature array(s) + label array(s)."""

    __slots__ = ("feature", "label")

    def __init__(self, feature, label=None):
        self.feature = feature
        self.label = label

    @staticmethod
    def from_ndarray(feature, label=None) -> "Sample":
        f = np.asarray(feature)
        l = None if label is None else np.asarray(label)
        return Sample(f, l)

    def feature_size(self):
        return self.feature.shape

    def label_size(self):
        return None if self.label is None else self.label.shape

    def __repr__(self):
        ls = None if self.label is None else self.label.shape
        return f"Sample(feature={self.feature.shape}, label={ls})"


class MiniBatch:
    """Batched input/target pair (pytrees of arrays with leading batch dim)."""

    __slots__ = ("input", "target")

    def __init__(self, input, target=None):
        self.input = input
        self.target = target

    def size(self) -> int:
        leaf = self.input
        while isinstance(leaf, (tuple, list, dict)):
            leaf = next(iter(leaf.values())) if isinstance(leaf, dict) \
                else leaf[0]
        return leaf.shape[0]

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """Sub-batch [offset, offset+length) (reference
        ``MiniBatch.scala:155``)."""

        def cut(x):
            if isinstance(x, dict):
                return {k: cut(v) for k, v in x.items()}
            if isinstance(x, (tuple, list)):
                return type(x)(cut(e) for e in x)
            return x[offset:offset + length]

        return MiniBatch(cut(self.input),
                         None if self.target is None else cut(self.target))

    def __repr__(self):
        return f"MiniBatch(size={self.size()})"


@dataclass
class PaddingParam:
    """Variable-length padding config (reference ``Transformer.scala``
    PaddingParam): pad every sequence in the batch to the longest (or to
    ``fixed_length``) with ``padding_value``.

    ``buckets``: pad to the smallest listed length >= the batch's
    natural max instead — under XLA each distinct padded length is a
    separate compile, so bucketing bounds the compile count to
    ``len(buckets)`` (the SURVEY §7 "recompilation storms" mitigation;
    the reference pads per-batch because the JVM has no such cost)."""

    padding_value: float = 0.0
    fixed_length: Optional[int] = None
    buckets: Optional[Sequence[int]] = None


def _stack_padded(arrays: Sequence[np.ndarray], param: Optional[PaddingParam]):
    """Stack arrays; if ragged in dim 0 (sequence), pad per PaddingParam."""
    shapes = {a.shape for a in arrays}
    if len(shapes) == 1 and param is None:
        return np.stack(arrays)
    if param is None:
        raise ValueError(
            f"ragged samples {sorted(shapes)} need a PaddingParam")
    max_len = param.fixed_length or max(a.shape[0] for a in arrays)
    if param.buckets is not None and param.fixed_length is None:
        fitting = [b for b in sorted(param.buckets) if b >= max_len]
        if not fitting:
            raise ValueError(
                f"sequence length {max_len} exceeds the largest bucket "
                f"{max(param.buckets)}")
        max_len = fitting[0]
    out_shape = (len(arrays), max_len) + arrays[0].shape[1:]
    out = np.full(out_shape, param.padding_value, dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        out[i, :a.shape[0]] = a
    return out


def batch_samples(samples: Sequence[Sample],
                  feature_padding: Optional[PaddingParam] = None,
                  label_padding: Optional[PaddingParam] = None) -> MiniBatch:
    """Collate samples into a MiniBatch (reference ``SampleToMiniBatch``
    internals)."""
    feats = _stack_padded([s.feature for s in samples], feature_padding)
    if samples[0].label is None:
        return MiniBatch(feats, None)
    labels = _stack_padded([np.asarray(s.label) for s in samples],
                           label_padding)
    return MiniBatch(feats, labels)


class SparseSample:
    """One example whose feature (or one of whose features) is a sparse
    1-D vector in COO form (reference ``Sample`` over ``SparseTensor``,
    ``DL/tensor/SparseTensor.scala:55-57``): ``indices[k]`` holds
    ``values[k]``, dense width ``size``.  ``dense`` optionally carries
    extra dense feature arrays alongside (the Wide&Deep layout)."""

    __slots__ = ("indices", "values", "size", "dense", "label")

    def __init__(self, indices, values, size: int, dense=None, label=None):
        self.indices = np.asarray(indices, np.int32).reshape(-1)
        self.values = np.asarray(values, np.float32).reshape(-1)
        assert self.indices.shape == self.values.shape
        self.size = int(size)
        if dense is not None and not isinstance(dense, (list, tuple)):
            dense = [dense]  # one dense side-feature, not a list of parts
        self.dense = dense
        self.label = None if label is None else np.asarray(label)

    @property
    def nnz(self) -> int:
        return len(self.indices)

    def __repr__(self):
        return (f"SparseSample(nnz={self.nnz}, size={self.size}, "
                f"dense={None if self.dense is None else 'yes'})")


class SparseMiniBatch(MiniBatch):
    """MiniBatch whose ``input`` begins with a batch-COO sparse feature
    (reference ``SparseMiniBatch``, ``DL/dataset/MiniBatch.scala:588``:
    per-batch COO tensors built from sparse samples).

    ``input`` is ``coo`` alone, or ``(coo, *dense_parts)`` when the
    samples carried dense side-features; ``coo`` is an
    ``nn.sparse.COOBatch`` ready for SparseLinear/LookupTableSparse.
    ``slice`` is unsupported: a flat COO stream has no per-sample
    alignment (sub-batching is the mesh's job under SPMD anyway)."""

    def size(self) -> int:
        coo = self.input[0] if isinstance(self.input, tuple) else self.input
        return coo.dense_shape[0]

    def slice(self, offset, length):
        raise TypeError("SparseMiniBatch does not support slice(); "
                        "shard the batch via the mesh instead")


def batch_sparse_samples(samples: Sequence[SparseSample],
                         nnz_buckets: Optional[Sequence[int]] = None
                         ) -> SparseMiniBatch:
    """Collate sparse samples into one batch-COO ``SparseMiniBatch``.

    The flat non-zero stream is padded to a STATIC length — the
    smallest fitting value of ``nnz_buckets``, or the next power of two
    — so XLA compiles one kernel per bucket instead of one per batch
    (the SURVEY §7 "recompilation storms" mitigation; padding entries
    are (row 0, col 0, value 0) and contribute nothing)."""
    from bigdl_tpu.nn.sparse import COOBatch
    import jax.numpy as jnp

    n = len(samples)
    total = sum(s.nnz for s in samples)
    if nnz_buckets is not None:
        fitting = [b for b in sorted(nnz_buckets) if b >= total]
        if not fitting:
            raise ValueError(f"batch nnz {total} exceeds the largest "
                             f"bucket {max(nnz_buckets)}")
        cap = fitting[0]
    else:
        cap = 1 if total == 0 else 1 << (total - 1).bit_length()
    row = np.zeros(cap, np.int32)
    col = np.zeros(cap, np.int32)
    val = np.zeros(cap, np.float32)
    pos = 0
    width = samples[0].size
    for i, s in enumerate(samples):
        assert s.size == width, "all sparse samples must share a width"
        row[pos:pos + s.nnz] = i
        col[pos:pos + s.nnz] = s.indices
        val[pos:pos + s.nnz] = s.values
        pos += s.nnz
    coo = COOBatch(jnp.asarray(row), jnp.asarray(col), jnp.asarray(val),
                   (n, width))
    if samples[0].dense is not None:
        dense = [np.stack([np.asarray(s.dense[i]) for s in samples])
                 for i in range(len(samples[0].dense))]
        inp = (coo, *dense)
    else:
        inp = coo
    label = None
    if samples[0].label is not None:
        label = np.stack([s.label for s in samples])
    return SparseMiniBatch(inp, label)
