"""bigdl_tpu.transform — vision-2.0 style data transforms.

Reference: ``DL/transform/vision/`` (30 files, 4,008 LoC).
"""

from bigdl_tpu.transform.vision import (
    ImageFeature, ImageFrame, LocalImageFrame, FeatureTransformer,
    Brightness, Contrast, Saturation, Hue, ChannelNormalize, PixelNormalizer,
    ChannelScaledNormalizer, Expand, Filler, HFlip, Resize, AspectScale,
    RandomAspectScale, RandomResize,
    CenterCrop, RandomCrop, FixedCrop, RandomAlterAspect, ChannelOrder,
    ColorJitter, Lighting, RandomTransformer, MatToFloats, ImageFrameToSample,
)
