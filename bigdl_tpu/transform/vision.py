"""Vision-2.0 image pipeline: ImageFeature, ImageFrame, FeatureTransformer
and the augmentation op set.

Reference: ``DL/transform/vision/image/`` —
``ImageFeature.scala:36`` (a hash-map record carrying bytes/OpenCV-mat/
floats/label/metadata through the pipeline), ``ImageFrame.scala``
(Local vs Distributed collection), ``FeatureTransformer.scala``
(composable ops), and 18 augmentation ops under ``augmentation/``
(Brightness/Hue/Saturation/Contrast/Expand/Filler/RandomAlterAspect/
RandomCropper/…).

TPU redesign: the reference's ops are JNI OpenCV calls on ``OpenCVMat``;
here the image payload is a float32 numpy HWC array and every op is pure
numpy — augmentation runs on TPU-VM host CPUs ahead of ``device_put``
(SURVEY §7 stage 5).  Interpolation-heavy ops (resize) use simple
nearest/bilinear numpy implementations, trading exact OpenCV parity for
zero native dependencies.  Distributed ImageFrame: the RDD wrapper
becomes "a per-host shard of features" — the mesh, not an RDD, is the
unit of distribution.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.utils.imgops import (ThreadRng, color_jitter, hsv_to_rgb,
                                    lighting_delta, resize_bilinear,
                                    rgb_to_hsv)

# single source of truth for the numeric kernels is utils/imgops — shared
# with the Sample-based transformers in dataset/image.py
_rgb_to_hsv = rgb_to_hsv
_hsv_to_rgb = hsv_to_rgb
_resize_bilinear = resize_bilinear


class ImageFeature(dict):
    """Mutable record flowing through the pipeline (reference
    ``ImageFeature.scala:36``).  Well-known keys mirror the reference's:
    ``floats`` (the HWC float32 image), ``label``, ``original_size``,
    ``uri``, plus anything a transformer wants to stash."""

    FLOATS = "floats"
    LABEL = "label"
    URI = "uri"
    ORIGINAL_SIZE = "originalSize"

    def __init__(self, image: Optional[np.ndarray] = None, label=None,
                 uri: Optional[str] = None, **kw):
        super().__init__(**kw)
        if image is not None:
            img = np.asarray(image, np.float32)
            self[self.FLOATS] = img
            self[self.ORIGINAL_SIZE] = img.shape
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.FLOATS]

    @image.setter
    def image(self, v: np.ndarray):
        self[self.FLOATS] = v

    @property
    def label(self):
        return self.get(self.LABEL)


class FeatureTransformer:
    """Composable ImageFeature→ImageFeature op (reference
    ``FeatureTransformer.scala``; compose with ``>>`` like dataset
    transformers)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError(type(self).__name__)

    def __call__(self, feature: ImageFeature) -> ImageFeature:
        return self.transform(feature)

    def __rshift__(self, other: "FeatureTransformer") -> "ChainedFeature":
        return ChainedFeature(self, other)


class ChainedFeature(FeatureTransformer):
    def __init__(self, a: FeatureTransformer, b: FeatureTransformer):
        self.a, self.b = a, b

    def transform(self, feature):
        return self.b(self.a(feature))


class ImageFrame:
    """Collection of ImageFeatures (reference ``ImageFrame.scala``).
    ``ImageFrame.read``/``array`` build a Local frame; the Distributed
    variant's role (an RDD of features) is covered by per-host sharding in
    ``dataset.DistributedDataSet`` — build samples first, then shard."""

    @staticmethod
    def array(images: Sequence, labels: Optional[Sequence] = None
              ) -> "LocalImageFrame":
        feats = [ImageFeature(img,
                              None if labels is None else labels[i])
                 for i, img in enumerate(images)]
        return LocalImageFrame(feats)

    @staticmethod
    def read(path: str, with_label: bool = False) -> "LocalImageFrame":
        """Read a directory of images into a Local frame (reference
        ``ImageFrame.read`` / ``DLImageReader``).  ``with_label=True``
        uses the ImageNet folder convention — one subdirectory per
        class, labels assigned by sorted subdirectory order."""
        import os
        from PIL import Image

        exts = (".jpg", ".jpeg", ".png", ".bmp")

        def load(p):
            return np.asarray(Image.open(p).convert("RGB"), np.float32)

        feats: List[ImageFeature] = []
        if with_label:
            classes = sorted(d for d in os.listdir(path)
                             if os.path.isdir(os.path.join(path, d)))
            for label, cls in enumerate(classes):
                cdir = os.path.join(path, cls)
                for fn in sorted(os.listdir(cdir)):
                    if fn.lower().endswith(exts):
                        feats.append(ImageFeature(
                            load(os.path.join(cdir, fn)),
                            label=np.int32(label),
                            uri=os.path.join(cls, fn)))
        else:
            for fn in sorted(os.listdir(path)):
                if fn.lower().endswith(exts):
                    feats.append(ImageFeature(
                        load(os.path.join(path, fn)), uri=fn))
        return LocalImageFrame(feats)


class LocalImageFrame(ImageFrame):
    def __init__(self, features: List[ImageFeature]):
        self.features = list(features)

    def transform(self, t: FeatureTransformer) -> "LocalImageFrame":
        self.features = [t(f) for f in self.features]
        return self

    def __rshift__(self, t: FeatureTransformer) -> "LocalImageFrame":
        return self.transform(t)

    def to_samples(self) -> List[Sample]:
        return [Sample(f.image, f.label) for f in self.features]

    def __len__(self):
        return len(self.features)


# ----------------------------------------------------------- pixel-level ops
class Brightness(FeatureTransformer):
    """Add a uniform delta (reference ``augmentation/Brightness.scala``)."""

    def __init__(self, delta_low: float, delta_high: float, seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        f.image = f.image + self._rng.uniform(self.low, self.high)
        return f


class Contrast(FeatureTransformer):
    """Scale around zero (reference ``augmentation/Contrast.scala``)."""

    def __init__(self, delta_low: float, delta_high: float, seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        f.image = f.image * self._rng.uniform(self.low, self.high)
        return f


class Saturation(FeatureTransformer):
    """Scale HSV saturation (reference ``augmentation/Saturation.scala``)."""

    def __init__(self, delta_low: float, delta_high: float, seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        hsv = _rgb_to_hsv(np.clip(f.image, 0, 255))
        hsv[..., 1] = np.clip(hsv[..., 1]
                              * self._rng.uniform(self.low, self.high), 0, 1)
        f.image = _hsv_to_rgb(hsv).astype(np.float32)
        return f


class Hue(FeatureTransformer):
    """Rotate HSV hue by a random delta in degrees (reference
    ``augmentation/Hue.scala``)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: int = 0):
        self.low, self.high = delta_low, delta_high
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        hsv = _rgb_to_hsv(np.clip(f.image, 0, 255))
        hsv[..., 0] = (hsv[..., 0]
                       + self._rng.uniform(self.low, self.high)) % 360.0
        f.image = _hsv_to_rgb(hsv).astype(np.float32)
        return f


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel (reference ``ChannelNormalize.scala``)."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def transform(self, f):
        f.image = (f.image - self.mean) / self.std
        return f


class ChannelScaledNormalizer(FeatureTransformer):
    """(x - mean_c) * scale per channel (reference
    ``augmentation/ChannelScaledNormalizer.scala:42`` — integer
    per-channel means with one shared scale factor)."""

    def __init__(self, mean_r: int, mean_g: int, mean_b: int,
                 scale: float):
        self.mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self.scale = float(scale)

    def transform(self, f):
        f.image = ((f.image - self.mean) * self.scale).astype(np.float32)
        return f


class PixelNormalizer(FeatureTransformer):
    """Subtract a per-pixel mean image (reference ``PixelNormalizer.scala``)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, f):
        f.image = f.image - self.means
        return f


class ChannelOrder(FeatureTransformer):
    """Swap RGB↔BGR (reference ``ChannelOrder.scala``)."""

    def transform(self, f):
        f.image = np.ascontiguousarray(f.image[..., ::-1])
        return f


# ------------------------------------------------------------ geometric ops
class Resize(FeatureTransformer):
    """Resize to (h, w) (reference ``augmentation/Resize.scala``)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def transform(self, f):
        f.image = _resize_bilinear(f.image, self.h, self.w)
        return f


class AspectScale(FeatureTransformer):
    """Scale the short edge to ``min_size`` keeping aspect ratio, capped at
    ``max_size`` (reference ``AspectScale.scala`` — the Faster-RCNN
    convention)."""

    def __init__(self, min_size: int, max_size: int = 1000):
        self.min_size, self.max_size = min_size, max_size

    def transform(self, f):
        h, w = f.image.shape[:2]
        scale = self.min_size / min(h, w)
        if scale * max(h, w) > self.max_size:
            scale = self.max_size / max(h, w)
        f.image = _resize_bilinear(f.image, int(round(h * scale)),
                                   int(round(w * scale)))
        f["scale"] = scale
        return f


class RandomResize(FeatureTransformer):
    """Resize the SHORT edge to a uniform random size in
    ``[min_size, max_size]``, scaling the long edge to preserve aspect
    ratio (reference ``augmentation/RandomResize.scala:32``)."""

    def __init__(self, min_size: int, max_size: int, seed: int = 0):
        if max_size < min_size:
            raise ValueError("max_size must be >= min_size")
        self.min_size, self.max_size = min_size, max_size
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        h, w = f.image.shape[:2]
        short = self.min_size + int(self._rng.uniform(
            1e-2, self.max_size - self.min_size + 1))
        if h < w:
            w = int(w / h * short)
            h = short
        else:
            h = int(h / w * short)
            w = short
        f.image = _resize_bilinear(f.image, h, w)
        return f


class RandomAspectScale(AspectScale):
    """Pick the short-edge target randomly from ``scales`` (reference
    ``RandomAspectScale.scala``)."""

    def __init__(self, scales: Sequence[int], max_size: int = 1000,
                 seed: int = 0):
        super().__init__(scales[0], max_size)
        self.scales = list(scales)
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        # no shared-state write (``self.min_size``) — transforms run on
        # multiple prefetch worker threads
        min_size = int(self._rng.choice(self.scales))
        h, w = f.image.shape[:2]
        scale = min_size / min(h, w)
        if scale * max(h, w) > self.max_size:
            scale = self.max_size / max(h, w)
        f.image = _resize_bilinear(f.image, int(round(h * scale)),
                                   int(round(w * scale)))
        f["scale"] = scale
        return f


class CenterCrop(FeatureTransformer):
    """(reference ``augmentation/CenterCrop.scala``)."""

    def __init__(self, crop_h: int, crop_w: int):
        self.ch, self.cw = crop_h, crop_w

    def transform(self, f):
        h, w = f.image.shape[:2]
        y, x = (h - self.ch) // 2, (w - self.cw) // 2
        f.image = np.ascontiguousarray(
            f.image[y:y + self.ch, x:x + self.cw])
        return f


class RandomCrop(FeatureTransformer):
    """(reference ``augmentation/RandomCropper.scala``)."""

    def __init__(self, crop_h: int, crop_w: int, pad: int = 0, seed: int = 0):
        self.ch, self.cw, self.pad = crop_h, crop_w, pad
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        img = f.image
        if self.pad:
            img = np.pad(img, ((self.pad, self.pad), (self.pad, self.pad))
                         + (((0, 0),) if img.ndim == 3 else ()))
        h, w = img.shape[:2]
        y = int(self._rng.integers(0, h - self.ch + 1))
        x = int(self._rng.integers(0, w - self.cw + 1))
        f.image = np.ascontiguousarray(img[y:y + self.ch, x:x + self.cw])
        return f


class FixedCrop(FeatureTransformer):
    """Crop a fixed normalized or absolute box (reference
    ``FixedCrop.scala``)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def transform(self, f):
        h, w = f.image.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        f.image = np.ascontiguousarray(
            f.image[int(y1):int(y2), int(x1):int(x2)])
        return f


class Expand(FeatureTransformer):
    """Place the image on a larger mean-filled canvas (reference
    ``augmentation/Expand.scala`` — SSD zoom-out)."""

    def __init__(self, means: Sequence[float] = (123.0, 117.0, 104.0),
                 max_expand_ratio: float = 4.0, seed: int = 0):
        self.means = np.asarray(means, np.float32)
        self.max_ratio = max_expand_ratio
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        img = f.image
        h, w = img.shape[:2]
        ratio = self._rng.uniform(1.0, self.max_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        canvas = np.broadcast_to(self.means, (nh, nw, img.shape[2])).copy() \
            if img.ndim == 3 else np.full((nh, nw), self.means.mean(),
                                          np.float32)
        y = int(self._rng.integers(0, nh - h + 1))
        x = int(self._rng.integers(0, nw - w + 1))
        canvas[y:y + h, x:x + w] = img
        f.image = canvas.astype(np.float32)
        f["expand_offset"] = (x, y, ratio)
        return f


class Filler(FeatureTransformer):
    """Fill a sub-rectangle with a constant (reference
    ``augmentation/Filler.scala`` — random-erasing style)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 value: float = 255.0):
        self.box = (x1, y1, x2, y2)
        self.value = value

    def transform(self, f):
        h, w = f.image.shape[:2]
        x1, y1, x2, y2 = self.box
        f.image[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        return f


class HFlip(FeatureTransformer):
    """(reference ``augmentation/HFlip.scala``)."""

    def __init__(self, threshold: float = 0.5, seed: int = 0):
        self.threshold = threshold
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        if self._rng.random() < self.threshold:
            f.image = np.ascontiguousarray(f.image[:, ::-1])
        return f


class RandomAlterAspect(FeatureTransformer):
    """Random-area/aspect crop then resize — the Inception training crop
    (reference ``augmentation/RandomAlterAspect.scala``)."""

    def __init__(self, min_area_ratio: float = 0.08,
                 max_area_ratio: float = 1.0,
                 min_aspect_ratio: float = 0.75, target_size: int = 224,
                 seed: int = 0):
        self.min_area, self.max_area = min_area_ratio, max_area_ratio
        self.min_aspect = min_aspect_ratio
        self.target = target_size
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        img = f.image
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = self._rng.uniform(self.min_area,
                                            self.max_area) * area
            aspect = self._rng.uniform(self.min_aspect, 1.0 / self.min_aspect)
            cw = int(round(math.sqrt(target_area * aspect)))
            ch = int(round(math.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                y = int(self._rng.integers(0, h - ch + 1))
                x = int(self._rng.integers(0, w - cw + 1))
                crop = img[y:y + ch, x:x + cw]
                f.image = _resize_bilinear(crop, self.target, self.target)
                return f
        f.image = _resize_bilinear(img, self.target, self.target)
        return f


class ColorJitter(FeatureTransformer):
    """Random brightness/contrast/saturation in random order (reference
    ``augmentation/ColorJitter.scala``)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, seed: int = 0):
        self.b, self.c, self.s = brightness, contrast, saturation
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        f.image = color_jitter(f.image, self._rng, self.b, self.c, self.s)
        return f


class Lighting(FeatureTransformer):
    """AlexNet PCA lighting (reference ``augmentation/Lighting.scala``)."""

    def __init__(self, alphastd: float = 0.1, seed: int = 0):
        self.alphastd = alphastd
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        f.image = f.image + lighting_delta(self._rng, self.alphastd)
        return f


class RandomTransformer(FeatureTransformer):
    """Apply the inner transformer with probability p (reference
    ``RandomTransformer.scala``)."""

    def __init__(self, inner: FeatureTransformer, prob: float,
                 seed: int = 0):
        self.inner = inner
        self.prob = prob
        self._rng = ThreadRng(seed, salt=type(self).__name__)

    def transform(self, f):
        return self.inner(f) if self._rng.random() < self.prob else f


class MatToFloats(FeatureTransformer):
    """No-op layout hook kept for API parity (reference
    ``MatToFloats.scala`` converts OpenCV Mat → float array; images here
    are already float arrays)."""

    def transform(self, f):
        f.image = np.asarray(f.image, np.float32)
        return f


class ImageFrameToSample(FeatureTransformer):
    """Attach a Sample built from (image, label) (reference
    ``ImageFrameToSample.scala``); ``to_chw`` transposes HWC→CHW."""

    def __init__(self, to_chw: bool = True):
        self.to_chw = to_chw

    def transform(self, f):
        img = f.image
        if self.to_chw and img.ndim == 3:
            img = np.ascontiguousarray(img.transpose(2, 0, 1))
        f["sample"] = Sample(img, f.label)
        return f
