"""Training-state schema: build, hash, and diff-validate on resume.

PR 4 taught the resume path to reject an opt_state written by the other
gradient-sync mode (``DistriOptimizer._check_resumed_opt_state``) by
sniffing the pytree shape.  This module generalizes that to the FULL
manifest: a snapshot records a structured description of the training
state it holds — parameter tree (shapes/dtypes), gradient-sync
configuration (enabled, bucket plan, wire dtype, shard count), and the
optimizer method — and resume compares it field-by-field against the
current run.  Any drift (grad_sync flipped, ``grad_bucket_bytes``
changed, a layer resized) fails LOUDLY with a diff-style message
instead of an opaque jit structure error three layers down.
"""

from __future__ import annotations

import hashlib
import json
from typing import List, Optional


class SchemaMismatchError(ValueError):
    """Resume state does not match the snapshot's schema."""


def describe_params(params) -> dict:
    """Param pytree → ``{leaf path: "shape:dtype"}`` (the architecture
    fingerprint; path strings come from ``jax.tree_util.keystr``)."""
    import jax
    import numpy as np
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        # .shape first: leaves may be ShapeDtypeStructs (eval_shape
        # fingerprints) that np.shape cannot coerce
        shape = getattr(leaf, "shape", None)
        shape = tuple(np.shape(leaf) if shape is None else shape)
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        out[key] = f"{shape}:{dtype}"
    return out


def build_schema(params, *, grad_sync: bool = False,
                 bucket_sizes: Optional[List[int]] = None,
                 wire_dtype: Optional[str] = None,
                 n_shard: Optional[int] = None,
                 optim_method: Optional[str] = None) -> dict:
    """The schema dict a snapshot manifest carries (JSON-able)."""
    gs: dict = {"enabled": bool(grad_sync)}
    if grad_sync:
        gs.update(bucket_sizes=[int(s) for s in (bucket_sizes or [])],
                  wire_dtype=str(wire_dtype), n_shard=int(n_shard or 1))
    return {
        "params": describe_params(params),
        "grad_sync": gs,
        "optim_method": optim_method,
    }


def schema_hash(schema: dict) -> str:
    """Stable short hash of the canonical JSON form (manifest display +
    quick equality)."""
    blob = json.dumps(schema, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def _diff_section(lines: List[str], label: str, saved, current) -> None:
    if saved != current:
        lines.append(f"  {label}:")
        lines.append(f"    - snapshot: {saved}")
        lines.append(f"    + current:  {current}")


def diff_schemas(saved: dict, current: dict) -> List[str]:
    """Human-readable diff lines (empty = compatible)."""
    lines: List[str] = []
    _diff_section(lines, "optim_method", saved.get("optim_method"),
                  current.get("optim_method"))
    sgs, cgs = saved.get("grad_sync") or {}, current.get("grad_sync") or {}
    if bool(sgs.get("enabled")) != bool(cgs.get("enabled")):
        _diff_section(lines, "grad_sync.enabled", sgs.get("enabled"),
                      cgs.get("enabled"))
    elif sgs.get("enabled"):
        for k in ("bucket_sizes", "wire_dtype", "n_shard"):
            _diff_section(lines, f"grad_sync.{k}", sgs.get(k), cgs.get(k))
    sp, cp = saved.get("params") or {}, current.get("params") or {}
    for key in sorted(set(sp) | set(cp)):
        _diff_section(lines, f"params{key}", sp.get(key, "<absent>"),
                      cp.get(key, "<absent>"))
    return lines


def validate_schema(saved: Optional[dict], current: dict,
                    source: str = "checkpoint") -> None:
    """Raise :class:`SchemaMismatchError` with the full diff when the
    snapshot's schema and the current run's disagree.  ``saved=None``
    (a legacy pre-manifest snapshot) validates nothing — the structural
    fallback checks in ``DistriOptimizer._check_resumed_opt_state``
    still apply."""
    if saved is None:
        return
    lines = diff_schemas(saved, current)
    if not lines:
        return
    hints = []
    sgs, cgs = (saved.get("grad_sync") or {}), \
        (current.get("grad_sync") or {})
    if bool(sgs.get("enabled")) != bool(cgs.get("enabled")):
        hints.append("resume with the matching grad_sync / "
                     "parameter_sharding setting")
    elif sgs.get("enabled") and sgs != cgs:
        hints.append("the bucket plan drifted — restore the original "
                     "mesh size / grad_bucket_bytes / grad_wire_dtype")
    if (saved.get("params") or {}) != (current.get("params") or {}):
        hints.append("the model architecture changed since the "
                     "snapshot was written")
    hints.append("or clear the checkpoint directory to start fresh")
    raise SchemaMismatchError(
        f"{source} schema mismatch — refusing to resume (the saved "
        "state would be silently reinterpreted):\n"
        + "\n".join(lines) + "\nhint: " + "; ".join(hints))
