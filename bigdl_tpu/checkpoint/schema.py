"""Training-state schema: build, hash, and diff-validate on resume.

PR 4 taught the resume path to reject an opt_state written by the other
gradient-sync mode (``DistriOptimizer._check_resumed_opt_state``) by
sniffing the pytree shape.  This module generalizes that to the FULL
manifest: a snapshot records a structured description of the training
state it holds — parameter tree (shapes/dtypes), gradient-sync
configuration (enabled, bucket plan, wire dtype, shard count), and the
optimizer method — and resume compares it field-by-field against the
current run.  Any drift (grad_sync flipped, ``grad_bucket_bytes``
changed, a layer resized) fails LOUDLY with a diff-style message
instead of an opaque jit structure error three layers down.
"""

from __future__ import annotations

import hashlib
import json
from typing import List, Optional, Tuple


class SchemaMismatchError(ValueError):
    """Resume state does not match the snapshot's schema."""


def describe_params(params) -> dict:
    """Param pytree → ``{leaf path: "shape:dtype"}`` (the architecture
    fingerprint; path strings come from ``jax.tree_util.keystr``)."""
    import jax
    import numpy as np
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        # .shape first: leaves may be ShapeDtypeStructs (eval_shape
        # fingerprints) that np.shape cannot coerce
        shape = getattr(leaf, "shape", None)
        shape = tuple(np.shape(leaf) if shape is None else shape)
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        out[key] = f"{shape}:{dtype}"
    return out


def build_schema(params, *, grad_sync: bool = False,
                 bucket_sizes: Optional[List[int]] = None,
                 wire_dtype: Optional[str] = None,
                 n_shard: Optional[int] = None,
                 optim_method: Optional[str] = None,
                 bucket_content: Optional[List[int]] = None) -> dict:
    """The schema dict a snapshot manifest carries (JSON-able).
    ``bucket_content`` is the UNPADDED element count per bucket — the
    world-size-invariant layout that elastic resume compares when the
    padded ``bucket_sizes`` are allowed to drift."""
    gs: dict = {"enabled": bool(grad_sync)}
    if grad_sync:
        gs.update(bucket_sizes=[int(s) for s in (bucket_sizes or [])],
                  wire_dtype=str(wire_dtype), n_shard=int(n_shard or 1))
        if bucket_content is not None:
            gs["bucket_content"] = [int(s) for s in bucket_content]
    return {
        "params": describe_params(params),
        "grad_sync": gs,
        "optim_method": optim_method,
    }


def schema_hash(schema: dict) -> str:
    """Stable short hash of the canonical JSON form (manifest display +
    quick equality)."""
    blob = json.dumps(schema, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def _diff_section(lines: List[str], label: str, saved, current) -> None:
    if saved != current:
        lines.append(f"  {label}:")
        lines.append(f"    - snapshot: {saved}")
        lines.append(f"    + current:  {current}")


def diff_schemas(saved: dict, current: dict,
                 elastic: bool = False) -> List[str]:
    """Human-readable diff lines (empty = compatible).

    ``elastic=True`` is the elastic-resume compatibility mode: the
    padded ``bucket_sizes`` and ``n_shard`` are ALLOWED to differ (the
    world size changed — that is the point), while everything that
    defines logical model identity stays strict: params, optim_method,
    wire_dtype, grad_sync.enabled, and — when both schemas record it —
    the world-size-invariant ``bucket_content`` layout."""
    lines: List[str] = []
    _diff_section(lines, "optim_method", saved.get("optim_method"),
                  current.get("optim_method"))
    sgs, cgs = saved.get("grad_sync") or {}, current.get("grad_sync") or {}
    if bool(sgs.get("enabled")) != bool(cgs.get("enabled")):
        _diff_section(lines, "grad_sync.enabled", sgs.get("enabled"),
                      cgs.get("enabled"))
    elif sgs.get("enabled"):
        keys = (("wire_dtype", "bucket_content") if elastic
                else ("bucket_sizes", "wire_dtype", "n_shard"))
        for k in keys:
            if elastic and k == "bucket_content" \
                    and (k not in sgs or k not in cgs):
                # pre-elastic snapshots don't record content sizes;
                # reshard_state's own structural checks still apply
                continue
            _diff_section(lines, f"grad_sync.{k}", sgs.get(k), cgs.get(k))
    sp, cp = saved.get("params") or {}, current.get("params") or {}
    for key in sorted(set(sp) | set(cp)):
        _diff_section(lines, f"params{key}", sp.get(key, "<absent>"),
                      cp.get(key, "<absent>"))
    return lines


def elastic_compatible(saved: Optional[dict],
                       current: dict) -> Tuple[bool, List[str]]:
    """Would an ELASTIC resume accept this snapshot?  Returns
    ``(verdict, diff_lines)`` — the operator-facing form behind
    ``tools.ckpt_inspect --schema``.  Legacy schema-less snapshots are
    compatible-with-caveats (diff lines name the missing schema)."""
    if saved is None:
        return True, ["(legacy snapshot: no schema — structural "
                      "checks apply at restore time)"]
    lines = diff_schemas(saved, current, elastic=True)
    return not lines, lines


def validate_schema(saved: Optional[dict], current: dict,
                    source: str = "checkpoint",
                    elastic: bool = False) -> None:
    """Raise :class:`SchemaMismatchError` with the full diff when the
    snapshot's schema and the current run's disagree.  ``saved=None``
    (a legacy pre-manifest snapshot) validates nothing — the structural
    fallback checks in ``DistriOptimizer._check_resumed_opt_state``
    still apply.  ``elastic=True`` tolerates world-size/bucket-padding
    drift (see :func:`diff_schemas`) instead of the hard refusal."""
    if saved is None:
        return
    lines = diff_schemas(saved, current, elastic=elastic)
    if not lines:
        return
    hints = []
    sgs, cgs = (saved.get("grad_sync") or {}), \
        (current.get("grad_sync") or {})
    if bool(sgs.get("enabled")) != bool(cgs.get("enabled")):
        hints.append("resume with the matching grad_sync / "
                     "parameter_sharding setting")
    elif sgs.get("enabled") and sgs != cgs:
        if elastic:
            hints.append("the bucket CONTENT layout drifted — an "
                         "elastic resume only tolerates world-size/"
                         "padding changes, not grad_bucket_bytes or "
                         "wire-dtype changes")
        else:
            hints.append("the bucket plan drifted — restore the "
                         "original mesh size / grad_bucket_bytes / "
                         "grad_wire_dtype (or resume elastically: "
                         "world-size drift alone is resumable)")
    if (saved.get("params") or {}) != (current.get("params") or {}):
        hints.append("the model architecture changed since the "
                     "snapshot was written")
    hints.append("or clear the checkpoint directory to start fresh")
    raise SchemaMismatchError(
        f"{source} schema mismatch — refusing to resume (the saved "
        "state would be silently reinterpreted):\n"
        + "\n".join(lines) + "\nhint: " + "; ".join(hints))
