"""bigdl_tpu.checkpoint — async, fault-tolerant checkpointing.

The TPU-native replacement for the reference's ``setCheckpoint`` +
retry-from-``model.N`` story (``DistriOptimizer.scala:505-531,
981-1061``):

- :mod:`~bigdl_tpu.checkpoint.snapshot` — atomic (tmp + fsync +
  rename), checksummed (per-array CRC32c manifest), data-only ``.npz``
  snapshots + the bounded background :class:`AsyncSnapshotWriter`;
- :mod:`~bigdl_tpu.checkpoint.manager` — :class:`CheckpointManager`:
  retention (``keep_last`` + ``keep_every``), latest-VALID discovery
  that skips torn/corrupt snapshots, full-training-state save and
  mid-epoch-EXACT ``restore_into``;
- :mod:`~bigdl_tpu.checkpoint.schema` — manifest schema build/diff so
  resume across grad_sync / bucket-plan / architecture drift fails
  loudly;
- :mod:`~bigdl_tpu.checkpoint.preemption` — SIGTERM/SIGINT →
  finish-block + final-snapshot + clean exit.

Inspect any snapshot without loading it:
``python -m tools.ckpt_inspect <dir-or-file>``.
"""

from bigdl_tpu.checkpoint.manager import CheckpointManager
from bigdl_tpu.checkpoint.preemption import PreemptionHandler
from bigdl_tpu.checkpoint.schema import (SchemaMismatchError, build_schema,
                                         diff_schemas, schema_hash,
                                         validate_schema)
from bigdl_tpu.checkpoint.snapshot import (AsyncSnapshotWriter,
                                           SnapshotError, capture_to_host,
                                           load_snapshot, read_manifest,
                                           verify_snapshot, write_snapshot)

__all__ = [
    "CheckpointManager", "PreemptionHandler", "AsyncSnapshotWriter",
    "SnapshotError", "SchemaMismatchError", "build_schema", "diff_schemas",
    "schema_hash", "validate_schema", "capture_to_host", "load_snapshot",
    "read_manifest", "verify_snapshot", "write_snapshot",
]
