"""CheckpointManager — retention, discovery, async save, exact resume.

Reference: ``Optimizer.setCheckpoint(path, trigger)`` +
``DistriOptimizer.scala:981-1061`` retry-from-``model.N``.  The file
naming (``model.<neval>``) is kept so old tooling and the shim's
``latest_checkpoint`` keep working; everything else is new:

- **async save** off the driver path: the driver pays device→host
  capture + a bounded enqueue (both measured — ``checkpoint/
  driver_stall_s`` histogram, ``checkpoint/stall_fraction`` gauge);
  serialization, CRC, fsync and retention GC run on the writer thread;
- **retention**: ``keep_last`` newest snapshots always survive;
  ``keep_every`` (e.g. 1000) additionally pins every N-th step forever
  — the classic "recent ring + sparse archive" policy;
- **latest-VALID discovery**: candidates are verified (manifest +
  streamed CRC) newest-first and a torn/corrupt snapshot is skipped,
  never loaded — the crash window of the old synchronous writer;
- **full-state save/restore**: params, model state, optimizer state
  (including grad_sync's ZeRO-1 master buckets), driver counters,
  the RNG seed and the dataset shuffle position, so
  :meth:`restore_into` resumes training mid-epoch EXACTLY (bitwise
  loss-sequence equality — the gate in ``tests/test_checkpoint.py``).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import List, Optional

from bigdl_tpu.checkpoint.snapshot import (AsyncSnapshotWriter,
                                           SnapshotError, capture_to_host,
                                           load_snapshot, read_manifest,
                                           verify_snapshot, write_snapshot)

logger = logging.getLogger("bigdl_tpu.checkpoint")

_SNAP_RE = re.compile(r"^model\.(\d+)$")


class CheckpointManager:
    """Snapshot lifecycle for one checkpoint directory.

    ``registry``: an optional ``telemetry.MetricRegistry`` — save
    duration, bytes, and the driver stall fraction land there (the
    driver passes its ``Metrics`` registry so the numbers share a
    snapshot with the pipeline-phase gauges).
    """

    def __init__(self, directory: str, keep_last: int = 5,
                 keep_every: int = 0, overwrite: bool = True,
                 async_save: bool = True, registry=None,
                 queue_depth: int = 2, flight=None):
        self.directory = directory
        self.keep_last = max(1, int(keep_last))
        self.keep_every = max(0, int(keep_every))
        self.overwrite = overwrite
        self._writer = AsyncSnapshotWriter(queue_depth) if async_save \
            else None
        self._registry = registry
        # optional telemetry.FlightRecorder + the run's trace context
        # (the driver stamps trace_id per run): checkpoint COMMITS are
        # flight events — the event fires on the writer thread after
        # fsync, so the black box records what actually reached disk,
        # not what was merely enqueued
        self.flight = flight
        self.trace_id: Optional[str] = None
        self._t_run_start: Optional[float] = None
        self._driver_stall_s = 0.0
        # step of the newest save THIS manager issued (None = none yet);
        # the preemption path reads it to skip a redundant final
        # snapshot when a trigger checkpoint just covered the same
        # iteration
        self.last_saved_step: Optional[int] = None
        # GC pin: the step latest_valid() last returned is excluded
        # from _gc until restore completes — a retention ring turning
        # over during a slow (e.g. elastic) restore must not delete the
        # snapshot mid-read.  _gc runs on the writer thread, the pin is
        # taken on the driver/restore thread, hence the lock.
        self._pin_lock = threading.Lock()
        self._pinned_step: Optional[int] = None  # guarded-by: _pin_lock
        os.makedirs(directory, exist_ok=True)

    # --------------------------------------------------------- discovery
    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"model.{int(step)}")

    def steps(self) -> List[int]:
        """Snapshot steps present on disk, ascending (no validity
        check)."""
        out = []
        for f in os.listdir(self.directory):
            m = _SNAP_RE.match(f)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # acquires: snapshot_pin
    def latest_valid(self, verify: bool = True) -> Optional[str]:
        """Newest snapshot that passes integrity verification; corrupt
        or torn candidates are logged and SKIPPED (never loaded) — the
        retry loop then resumes from the last good state instead of
        crashing again on a bad file.

        The returned snapshot is PINNED against ``keep_last`` GC until
        :meth:`unpin` runs (``restore``/``restore_into`` release it on
        every path, success or raise) — otherwise a retention ring
        turning over during a slow restore could delete the snapshot
        between this verify pass and the load."""
        for step in reversed(self.steps()):
            path = self.path_for(step)
            ok, detail = verify_snapshot(path) if verify else (True, "")
            if ok:
                with self._pin_lock:
                    self._pinned_step = step  # acquires: snapshot_pin
                return path
            logger.warning("checkpoint discovery: skipping %s (%s)",
                           path, detail)
            if self._registry is not None:
                self._registry.counter(
                    "checkpoint/corrupt_skipped").inc()
        return None

    # releases: snapshot_pin
    def unpin(self) -> None:
        """Release the :meth:`latest_valid` GC pin (idempotent)."""
        with self._pin_lock:
            self._pinned_step = None  # releases: snapshot_pin

    # -------------------------------------------------------------- save
    def mark_run_start(self) -> None:
        """Anchor the stall-fraction denominator at driver-loop start."""
        self._t_run_start = time.perf_counter()
        self._driver_stall_s = 0.0

    # replay-boundary: callers reach save() only at block edges (the
    # producing block is synced — see snapshot.capture_to_host)
    def save(self, step: int, params, model_state=None, opt_state=None,
             driver_state: Optional[dict] = None,
             run_state: Optional[dict] = None,
             schema: Optional[dict] = None, sync: bool = False) -> str:
        """Capture + commit one snapshot.

        Driver-path cost: the device→host capture (at a replay
        boundary the producing block is already synced — see
        ``snapshot.capture_to_host``) plus a bounded enqueue; the
        expensive serialize/CRC/fsync/GC runs on the writer thread.
        ``sync=True`` (or ``async_save=False``) commits inline —
        the preemption path and the legacy shim use that.

        Returns the path the snapshot commits to."""
        t0 = time.perf_counter()
        path = self.path_for(step)
        if os.path.exists(path) and not self.overwrite:
            raise FileExistsError(
                f"{path} exists (reference: overWriteCheckpoint not set)")
        host = capture_to_host((params, model_state, opt_state))
        hp, hm, ho = host
        drv = dict(driver_state) if driver_state else None
        run = dict(run_state) if run_state else None

        def job():
            t_w0 = time.perf_counter()
            write_snapshot(path, params=hp, model_state=hm, opt_state=ho,
                           driver_state=drv, run_state=run, step=step,
                           schema=schema, overwrite=self.overwrite)
            self._gc()
            if self._registry is not None:
                reg = self._registry
                reg.histogram("checkpoint/save_s").observe(
                    time.perf_counter() - t_w0)
                reg.counter("checkpoint/bytes_written").inc(
                    _tree_bytes(host))
                reg.counter("checkpoint/snapshots_committed").inc()
            if self.flight is not None:
                self.flight.record("checkpoint_commit", cat="driver",
                                   trace_id=self.trace_id, step=step,
                                   path=path)
            logger.info("checkpoint saved to %s", path)

        # async_save is construction-time config — identical on every
        # process  # replicated-by: config-derived
        if sync or self._writer is None:
            job()
        else:
            # context travels with the job: a deferred write error
            # names exactly which snapshot was lost
            self._writer.submit(job, context=f"step {step} → {path}")
        self.last_saved_step = int(step)
        stall = time.perf_counter() - t0
        self._driver_stall_s += stall
        if self._registry is not None:
            self._registry.histogram(
                "checkpoint/driver_stall_s").observe(stall)
            self._registry.gauge("checkpoint/stall_fraction").set(
                self.stall_fraction())
        return path

    def stall_fraction(self) -> float:
        """Cumulative driver-side checkpoint time over run wall time —
        the number the async path exists to keep near zero (bench rider
        ``checkpoint_stall_fraction``)."""
        if self._t_run_start is None:
            return 0.0
        wall = time.perf_counter() - self._t_run_start
        return self._driver_stall_s / wall if wall > 0 else 0.0

    def _gc(self) -> None:
        """Retention: newest ``keep_last`` always survive; with
        ``keep_every=N`` every snapshot whose step is a multiple of N
        is pinned too.  Runs on the writer thread after each commit."""
        steps = self.steps()
        keep = set(steps[-self.keep_last:])
        if self.keep_every:  # replicated-by: config-derived
            keep.update(s for s in steps
                        if s and s % self.keep_every == 0)
        with self._pin_lock:
            pinned = self._pinned_step
        if pinned is not None:
            keep.add(pinned)  # a restore is reading this snapshot
        for s in steps:
            if s not in keep:
                try:
                    os.unlink(self.path_for(s))
                except OSError:  # already gone — racing GC is benign
                    pass

    def wait(self) -> None:
        """Block until every pending async save committed (surfaces
        deferred write errors)."""
        if self._writer is not None:
            self._writer.drain()

    def close(self, raise_errors: bool = True) -> None:
        if self._writer is not None:
            self._writer.close(raise_errors=raise_errors)

    # ----------------------------------------------------------- restore
    # acquires: snapshot_pin
    def restore(self, path: Optional[str] = None, *,
                verified: bool = False) -> dict:
        """Load a snapshot blob (latest valid when ``path`` is None).
        ``verified=True``: the caller's path already came from
        :meth:`latest_valid`, whose streamed CRC pass covers the whole
        file — skip the second end-to-end read.  Raises SnapshotError
        when nothing loadable exists.

        On success the snapshot stays pinned against GC (ownership of
        the pin passes to the caller — ``restore_into`` releases it
        once the state is applied); on ANY raise the pin is released
        here, so a failed restore cannot wedge retention."""
        try:
            if path is None:
                path = self.latest_valid()
                if path is None:
                    raise SnapshotError(
                        f"no valid checkpoint under {self.directory}")
                verified = True
            return load_snapshot(path, verify=not verified)
        except BaseException:
            self.unpin()
            raise

    def manifest(self, path: Optional[str] = None) -> Optional[dict]:
        if path is None:
            try:
                path = self.latest_valid()
                if path is None:
                    return None
                return read_manifest(path)
            finally:
                # manifest inspection holds no blob afterwards — the
                # discovery pin it took must not outlive the call
                self.unpin()
        return read_manifest(path)

    def restore_into(self, optimizer, path: Optional[str] = None, *,
                     verified: bool = False) -> dict:
        """Apply a snapshot to an :class:`~bigdl_tpu.optim.optimizer.
        Optimizer` so its next ``optimize()`` resumes mid-epoch
        EXACTLY: model params/state, optimizer state (validated against
        the saved schema at optimize() time), driver counters, RNG seed
        and the dataset shuffle position.  Returns the blob.

        The snapshot stays GC-pinned for the whole application (the
        caller's ``latest_valid`` pin, or the one ``restore`` takes);
        the ``finally`` releases it on every path, raise included."""
        try:
            blob = self.restore(path, verified=verified)
            manifest_schema = (blob.get("manifest") or {}).get("schema")
            if manifest_schema is not None:
                # architecture drift is checked BEFORE the snapshot's
                # params overwrite the model (afterwards the drift is
                # invisible — the restored params ARE the old
                # architecture); grad_sync / bucket-plan drift is
                # checked at optimize(), where the sync mode is resolved
                from bigdl_tpu.checkpoint.schema import validate_schema
                cur = getattr(optimizer, "_model_params_schema",
                              lambda: None)()
                if cur is not None:
                    validate_schema(
                        {"params": manifest_schema.get("params")},
                        {"params": cur}, source="restore_into")
            optimizer.model._params = blob["params"]
            optimizer.model._state = blob["model_state"]
            optimizer._resume_opt_state = blob["opt_state"]
            manifest = blob.get("manifest") or {}
            optimizer._resume_schema = manifest.get("schema")
            if blob["driver_state"]:
                optimizer.set_state(blob["driver_state"])
            run = blob.get("run") or {}
            if run.get("seed") is not None:
                optimizer.set_seed(int(run["seed"]))
            pos = run.get("dataset_position")
            restore_pos = getattr(optimizer.dataset, "restore_position",
                                  None)
            if pos and restore_pos is not None:
                restore_pos(pos)
            return blob
        finally:
            self.unpin()


def _tree_bytes(tree) -> int:
    import jax
    import numpy as np
    return int(sum(getattr(l, "nbytes", 0)
                   for l in jax.tree_util.tree_leaves(tree)
                   if isinstance(l, np.ndarray)))
