"""Preemption handling — turn SIGTERM/SIGINT into a clean final snapshot.

TPU pods (and every spot/preemptible pool) deliver eviction as a signal
with a grace window.  The handler here only RECORDS the request — the
training driver polls :attr:`triggered` at block boundaries, finishes
the in-flight block (so the saved state sits exactly on a replayed
iteration boundary — the bitwise-resume invariant), writes one final
synchronous snapshot, and returns from ``optimize()`` cleanly with
``state["preempted"] = True``.

Doing real work inside a signal handler (fsync, device syncs) is how
checkpoints get torn; a one-line flag set is async-signal-safe by
construction.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger("bigdl_tpu.checkpoint")

DEFAULT_SIGNALS: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)


class PreemptionHandler:
    """Installable signal→flag bridge.

    Use as a context manager (the driver does) or install()/uninstall()
    explicitly.  Installation outside the main thread is a documented
    no-op (CPython only delivers signals to the main thread, and
    ``signal.signal`` raises elsewhere) — ``installed`` stays False and
    ``triggered`` can still be set programmatically via
    :meth:`request` (tests, external schedulers).
    """

    def __init__(self, signals: Tuple[int, ...] = DEFAULT_SIGNALS):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev: Dict[int, object] = {}
        self.installed = False
        self.signum: Optional[int] = None

    # -- signal side ----------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        # flag only — everything heavy happens on the driver thread
        self.signum = signum
        self._event.set()

    def request(self) -> None:
        """Programmatic preemption (tests / cluster agents)."""
        self._event.set()

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    # -- lifecycle ------------------------------------------------------
    def install(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "preemption handler not installed: signal handlers can "
                "only be set from the main thread (use request() to "
                "trigger programmatically)")
            return self
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        self.installed = True
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):  # pragma: no cover - teardown
                pass
        self._prev.clear()
        self.installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
