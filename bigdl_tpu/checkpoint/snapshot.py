"""Atomic, checksummed, async-committable training-state snapshots.

Reference: ``Optimizer.setCheckpoint`` writes ``model.<neval>`` via
``File.save`` (``DistriOptimizer.scala:505-531``) — a synchronous,
non-atomic Java serialization the retry loop then trusts blindly.  This
module is the TPU-native replacement at the file layer:

- **data-only format**: the snapshot stays a ``.npz`` archive (arrays +
  a JSON skeleton describing the pytree), the same pickle-free wire the
  old ``utils/checkpoint.py`` used — loading a snapshot from an
  untrusted directory can never execute code.  v3 adds a
  ``__manifest__`` member: step/schema/per-array CRC32c metadata that
  can be read (and the whole file integrity-verified) WITHOUT
  deserializing a single array — that is what lets discovery skip a
  torn or bit-flipped snapshot instead of loading garbage;
- **atomic commit**: write to ``<name>.tmp`` → flush → ``fsync`` →
  ``os.replace`` → best-effort directory fsync.  A crash mid-write
  leaves a ``.tmp`` the discovery never considers; a crash mid-rename
  leaves either the old file or the new one, never a hybrid;
- **async hand-off**: :class:`AsyncSnapshotWriter` runs the expensive
  part (serialize + CRC + fsync) on ONE bounded background thread.  The
  driver's cost is the device→host capture plus a queue put — the
  capture itself rides the one-block-behind discipline (see
  :func:`capture_to_host`).

Device-fetch discipline (GL107): :func:`capture_to_host` is called by
the driver at a **replay boundary**, i.e. after the one-block-behind
loss fetch has already synced the producing block — the ``device_get``
here waits on a D2H copy of arrays whose compute is DONE, never drains
the dispatch pipeline, and must never move earlier than that boundary.
See the graftlint catalog note "snapshot fetches ride the replay
boundary".
"""

from __future__ import annotations

import json
import os
import queue
import threading
import zipfile
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

FORMAT_VERSION = 3
MANIFEST_MEMBER = "__manifest__"
META_MEMBER = "__meta__"
FORMAT_NAME = "bigdl_tpu-snapshot"

_CRC_CHUNK = 1 << 20


class SnapshotError(ValueError):
    """A snapshot failed to parse or verify (torn file, CRC mismatch,
    foreign format).  Discovery treats this as "skip", direct loads
    surface it."""


# ----------------------------------------------------------------- crc32c
try:  # C extension when the host has it (10-100x the table loop)
    import crc32c as _crc32c_mod

    def _crc32c_update(data, crc: int) -> int:
        return _crc32c_mod.crc32c(bytes(data), crc)
except ImportError:  # pragma: no cover - env without the C extension
    _crc32c_mod = None

if _crc32c_mod is None:
    from bigdl_tpu.utils.summary import crc32c as _crc32c_bytes

    def _crc32c_update(data, crc: int) -> int:
        # summary.crc32c folds the pre/post inversion per call; chain
        # chunks by re-inverting around the table loop
        return _crc32c_bytes(bytes(data), crc)


def crc32c_of(buf, crc: int = 0) -> int:
    """CRC32-C (Castagnoli) of a bytes-like/memoryview, chunked so a
    multi-GB array never needs a second contiguous copy."""
    view = memoryview(buf).cast("B")
    for off in range(0, len(view), _CRC_CHUNK):
        crc = _crc32c_update(view[off:off + _CRC_CHUNK], crc)
    return crc


def _array_crc(arr: np.ndarray) -> Tuple[int, int]:
    """(crc32c, nbytes) over the C-order bytes — exactly what
    ``np.save`` stores for the C-contiguous array we hand it."""
    arr = np.ascontiguousarray(arr)
    view = arr.reshape(-1).view(np.uint8) if arr.size else arr.tobytes()
    return crc32c_of(view), arr.nbytes


# ------------------------------------------------------- pytree <-> arrays
def to_host(tree):
    """Device pytree → numpy pytree (blocking; see capture_to_host for
    the driver-path discipline)."""
    import jax
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def to_device(tree):
    import jax.numpy as jnp
    import jax
    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, tree)


def capture_to_host(tree):
    """Snapshot capture on the driver path.

    MUST be called at a replay boundary: the one-block-behind loss
    fetch has already synced the block that produced these arrays, so
    the ``device_get`` below pays only the D2H copy — it cannot drain
    the dispatch pipeline (the GL107 discipline; catalog note "snapshot
    fetches ride the replay boundary").  The copy also protects the
    data from the NEXT block's donation: once on host, the device
    buffers are free to be consumed.
    """
    import jax
    return jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), tree)


def encode_tree(tree, arrays: list):
    """Pytree → JSON-able skeleton; array leaves appended to ``arrays``
    and referenced by index.  (The v2 wire of utils/checkpoint — moved
    here; the shim re-exports it.)"""
    if isinstance(tree, dict):
        return {"t": "dict",
                "k": list(tree.keys()),
                "v": [encode_tree(tree[k], arrays) for k in tree.keys()]}
    if isinstance(tree, (list, tuple)):
        return {"t": "list" if isinstance(tree, list) else "tuple",
                "v": [encode_tree(x, arrays) for x in tree]}
    if tree is None or isinstance(tree, (bool, int, float, str)):
        return {"t": "py", "v": tree}
    arr = np.asarray(tree)
    if arr.dtype.name == "bfloat16":
        # npz can't store ml_dtypes without pickle; round-trip via uint16
        arrays.append(arr.view(np.uint16))
        return {"t": "arr", "i": len(arrays) - 1, "d": "bfloat16"}
    arrays.append(arr)
    return {"t": "arr", "i": len(arrays) - 1}


def decode_tree(node, arrays):
    t = node["t"]
    if t == "dict":
        return {k: decode_tree(v, arrays)
                for k, v in zip(node["k"], node["v"])}
    if t == "list":
        return [decode_tree(v, arrays) for v in node["v"]]
    if t == "tuple":
        return tuple(decode_tree(v, arrays) for v in node["v"])
    if t == "py":
        return node["v"]
    arr = arrays[f"a{node['i']}"]
    if node.get("d") == "bfloat16":
        import ml_dtypes
        arr = arr.view(ml_dtypes.bfloat16)
    return arr


# ------------------------------------------------------------ write / read
def write_snapshot(path: str, *, params, model_state=None, opt_state=None,
                   driver_state: Optional[dict] = None,
                   run_state: Optional[dict] = None,
                   step: Optional[int] = None,
                   schema: Optional[dict] = None,
                   overwrite: bool = True) -> str:
    """Serialize + commit one snapshot atomically.  Everything here is
    host work (the caller already pulled the trees to host) — safe to
    run on the background writer thread.

    Returns the committed path.  With ``overwrite=False`` an existing
    ``path`` raises ``FileExistsError`` (the reference's
    ``overWriteCheckpoint`` unset behavior, now real)."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(
            f"{path} exists (reference: overWriteCheckpoint not set)")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays: List[np.ndarray] = []
    skeleton = {
        "version": FORMAT_VERSION,
        "params": encode_tree(params, arrays),
        "model_state": encode_tree(model_state, arrays)
        if model_state is not None else None,
        "opt_state": encode_tree(opt_state, arrays)
        if opt_state is not None else None,
        "driver_state": dict(driver_state) if driver_state else None,
        "run": dict(run_state) if run_state else None,
    }
    arrays = [np.ascontiguousarray(a) for a in arrays]
    entries = []
    total = 0
    for i, a in enumerate(arrays):
        crc, nbytes = _array_crc(a)
        total += nbytes
        entries.append({"name": f"a{i}", "crc32c": crc, "nbytes": nbytes,
                        "shape": list(a.shape), "dtype": a.dtype.name})
    meta_bytes = json.dumps(skeleton).encode()
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "step": int(step) if step is not None
        else (driver_state or {}).get("neval"),
        "epoch": (driver_state or {}).get("epoch"),
        "arrays": entries,
        "total_bytes": total,
        # the skeleton member is covered too: a bit-flip in __meta__
        # must fail verification exactly like one in an array, or the
        # latest-VALID fallback would hand a corrupt file to np.load
        "meta_crc32c": crc32c_of(meta_bytes),
        "meta_nbytes": len(meta_bytes),
        "schema": schema,
    }
    if schema is not None:
        from bigdl_tpu.checkpoint.schema import schema_hash
        manifest["schema_hash"] = schema_hash(schema)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        # stream straight to the file: no in-memory copy of the archive
        np.savez(
            f,
            **{META_MEMBER: np.frombuffer(meta_bytes, dtype=np.uint8),
               MANIFEST_MEMBER: np.frombuffer(
                json.dumps(manifest).encode(), dtype=np.uint8)},
            **{e["name"]: a for e, a in zip(entries, arrays)})
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic: a crash never leaves a torn snapshot
    _fsync_dir(os.path.dirname(path) or ".")
    return path


def _fsync_dir(dirname: str) -> None:
    """Make the rename durable (the file itself was fsync'd before the
    replace).  Best-effort: not every filesystem supports it."""
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic fs
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def read_manifest(path: str) -> Optional[dict]:
    """The snapshot's manifest WITHOUT touching any array member.
    Returns None for a pre-manifest (v2) archive; raises SnapshotError
    when the file is not a readable snapshot at all."""
    try:
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
            if META_MEMBER + ".npy" not in names:
                raise SnapshotError(
                    f"{path}: no {META_MEMBER} member — not a bigdl_tpu "
                    "checkpoint (data-only policy: foreign formats are "
                    "never auto-loaded)")
            if MANIFEST_MEMBER + ".npy" not in names:
                return None  # legacy v2: valid, just unverifiable
            with zf.open(MANIFEST_MEMBER + ".npy") as fp:
                raw = _read_npy_payload(fp)
            return json.loads(raw.decode())
    except (zipfile.BadZipFile, OSError, ValueError, KeyError) as e:
        if isinstance(e, SnapshotError):
            raise
        raise SnapshotError(f"{path}: unreadable snapshot ({e})") from e


def _read_npy_header(fp):
    """(shape, fortran_order, dtype) of an open .npy member stream,
    consuming exactly the header bytes."""
    version = np.lib.format.read_magic(fp)
    if version == (1, 0):
        return np.lib.format.read_array_header_1_0(fp)
    if version == (2, 0):
        return np.lib.format.read_array_header_2_0(fp)
    raise SnapshotError(f"unsupported .npy version {version}")


def _read_npy_payload(fp) -> bytes:
    shape, _, dtype = _read_npy_header(fp)
    n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return fp.read(n)


def verify_snapshot(path: str, deep: bool = True) -> Tuple[bool, str]:
    """Integrity check WITHOUT materializing arrays: manifest is read,
    then (``deep=True``) every array member is streamed in chunks
    through CRC32c and compared against the manifest.  Returns
    ``(ok, detail)`` — never raises for a corrupt file, so discovery
    can fall back to the previous snapshot."""
    try:
        manifest = read_manifest(path)
    except SnapshotError as e:
        return False, str(e)
    if manifest is None:
        return True, "legacy (v2, no manifest — integrity unverifiable)"
    if not deep:
        return True, "manifest ok (arrays unverified)"
    members = [(e["name"] + ".npy", e["crc32c"], e["nbytes"])
               for e in manifest["arrays"]]
    if "meta_crc32c" in manifest:
        members.append((META_MEMBER + ".npy", manifest["meta_crc32c"],
                        manifest["meta_nbytes"]))
    try:
        with zipfile.ZipFile(path) as zf:
            for member, want_crc, want_bytes in members:
                crc = 0
                nbytes = 0
                with zf.open(member) as fp:
                    _read_npy_header(fp)
                    while True:
                        chunk = fp.read(_CRC_CHUNK)
                        if not chunk:
                            break
                        crc = _crc32c_update(chunk, crc)
                        nbytes += len(chunk)
                if nbytes != want_bytes:
                    return False, (f"{member}: {nbytes} bytes on disk, "
                                   f"manifest says {want_bytes} "
                                   "(torn write)")
                if crc != want_crc:
                    return False, (f"{member}: crc32c {crc:#010x} != "
                                   f"manifest {want_crc:#010x} "
                                   "(corrupt data)")
    except (zipfile.BadZipFile, OSError, KeyError, ValueError) as e:
        return False, f"verification failed: {e}"
    return True, f"ok ({len(manifest['arrays'])} arrays, " \
                 f"{manifest['total_bytes']} bytes)"


def load_snapshot(path: str, verify: bool = True) -> dict:
    """Load a snapshot → dict with params / model_state / opt_state /
    driver_state / run / manifest (device arrays).  ``verify=True``
    streams the CRC check FIRST so a corrupt file raises
    :class:`SnapshotError` before any array is deserialized.
    ``allow_pickle`` stays False: data-only by construction."""
    if verify:
        ok, detail = verify_snapshot(path)
        if not ok:
            raise SnapshotError(f"{path}: refusing to load — {detail}")
    try:
        with np.load(path, allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    except (ValueError, OSError, KeyError, zipfile.BadZipFile) as e:
        raise SnapshotError(
            f"{path} is not a bigdl_tpu (npz) checkpoint — legacy or "
            "foreign formats are not auto-loaded (data-only policy); "
            f"original error: {e}") from e
    skeleton = json.loads(bytes(arrays.pop(META_MEMBER)).decode())
    manifest_raw = arrays.pop(MANIFEST_MEMBER, None)
    manifest = json.loads(bytes(manifest_raw).decode()) \
        if manifest_raw is not None else None
    return {
        "params": to_device(decode_tree(skeleton["params"], arrays)),
        "model_state": to_device(decode_tree(skeleton["model_state"],
                                             arrays))
        if skeleton["model_state"] is not None else None,
        "opt_state": to_device(decode_tree(skeleton["opt_state"], arrays))
        if skeleton["opt_state"] is not None else None,
        "driver_state": skeleton["driver_state"],
        "run": skeleton.get("run"),
        "manifest": manifest,
    }


# --------------------------------------------------------- async hand-off
class AsyncSnapshotWriter:
    """ONE bounded background thread running snapshot-commit jobs in
    submission order.

    ``submit(job)`` enqueues a zero-arg callable and returns
    immediately; when the queue (default depth 2) is full it BLOCKS —
    bounded backpressure, so a slow disk can delay the driver but never
    buffer an unbounded pile of multi-GB host copies.  A failed job is
    remembered and re-raised (wrapped) on the next ``submit``/``drain``
    — checkpoint I/O errors fail the run loudly instead of evaporating
    on a daemon thread.
    """

    def __init__(self, capacity: int = 2):
        # queue items: (job, context) — context is the human label
        # ("step N → path") a deferred error is reported under, because
        # by the time the error surfaces the failing submit is long gone
        self._q: "queue.Queue[Optional[tuple]]" = \
            queue.Queue(maxsize=max(1, int(capacity)))
        self._lock = threading.Lock()
        # deferred-failure cell: set by the writer thread, consumed
        # (and cleared) by submit/drain on the driver thread
        self._error: Optional[BaseException] = None  # guarded-by: _lock
        # guarded-by: _lock
        self._error_context: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ckpt-writer", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                job, context = item
                job()
            except BaseException as e:  # surfaced on next submit/drain
                with self._lock:
                    self._error = e
                    self._error_context = context
            finally:
                self._q.task_done()

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
            ctx, self._error_context = self._error_context, None
        if err is not None:
            what = f" ({ctx})" if ctx else ""
            raise RuntimeError(
                f"async checkpoint write failed{what} — training state "
                f"was NOT durably saved") from err

    def submit(self, job: Callable[[], Any],
               context: Optional[str] = None) -> None:
        """Enqueue one commit job.  ``context`` names what the job was
        writing ("step N → path") so a deferred failure can report
        exactly which snapshot was lost — rollback policy logs what it
        fell back from."""
        if self._closed:
            raise RuntimeError("AsyncSnapshotWriter is closed")
        self._raise_pending()
        self._ensure_thread()
        self._q.put((job, context))  # blocks when the queue is full

    def drain(self) -> None:
        """Block until every submitted job committed; re-raise any
        deferred write error."""
        self._q.join()
        self._raise_pending()

    def close(self, raise_errors: bool = True) -> None:
        """Drain, stop the thread.  ``raise_errors=False`` swallows
        deferred errors (teardown on an already-failing run)."""
        self._closed = True
        self._q.join()
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=30.0)
        if raise_errors:
            self._raise_pending()
        else:
            with self._lock:
                self._error = None

    @property
    def pending(self) -> int:
        return self._q.unfinished_tasks
