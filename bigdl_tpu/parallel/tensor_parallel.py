"""Tensor (model) parallelism via sharding annotations.

No reference analog (SURVEY §2.9: TP absent in BigDL) — first-class here.
Design: Megatron-style column/row parameter splits expressed as
``PartitionSpec``s over the ``model`` mesh axis; **GSPMD inserts the
collectives** (all-gather/reduce-scatter around the split matmuls) — no
hand-written communication, the scaling-book recipe.

Modules advertise their own sharding via ``param_specs()`` (mirroring the
pytree their ``init`` returns); ``build_param_specs`` walks a model and
fills ``P()`` (replicated) for everything that doesn't opt in.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import PartitionSpec as P

from bigdl_tpu.nn.module import Container, Module

tmap = jax.tree_util.tree_map


def build_param_specs(module: Module, params):
    """Pytree of PartitionSpec matching ``params``.  Traversal follows
    ``Module.spec_children()`` (single-child delegation for wrappers like
    TimeDistributed/Recurrent, keyed dicts for containers), so shard
    annotations survive arbitrary nesting."""
    own = getattr(module, "param_specs", None)
    if own is not None:
        sp = own()
        if sp is not None:
            return sp
    children = module.spec_children()
    if children is None:
        return tmap(lambda _: P(), params)
    if isinstance(children, Module):
        return build_param_specs(children, params)
    return {k: build_param_specs(children[k], v) if k in children
            else tmap(lambda _: P(), v)
            for k, v in params.items()}


def column_parallel_linear_specs(with_bias: bool = True,
                                 axis: str = "model"):
    """Split the OUTPUT features: weight (out, in) → P(axis, None).
    Activations come out sharded on the feature dim."""
    sp = {"weight": P(axis, None)}
    if with_bias:
        sp["bias"] = P(axis)
    return sp


def row_parallel_linear_specs(with_bias: bool = True, axis: str = "model"):
    """Split the INPUT features: weight (out, in) → P(None, axis); the
    matmul produces partial sums that GSPMD all-reduces.  Bias replicated."""
    sp = {"weight": P(None, axis)}
    if with_bias:
        sp["bias"] = P()
    return sp


# The concrete opt-ins live on the modules themselves: Linear(shard=
# "column"/"row") and MultiHeadAttention(shard=True) implement
# ``param_specs()`` using the helpers above (see layers.py / attention.py).
