"""Ring attention — sequence/context parallelism over a mesh axis.

No reference analog (SURVEY §5: long-context absent from BigDL) — required
first-class capability of the TPU build: sequences longer than one chip's
HBM are sharded over the ``seq`` mesh axis, and attention runs blockwise
while K/V shards rotate around the ring via ``lax.ppermute`` over ICI
(Liu et al., "Ring Attention with Blockwise Transformers", 2023 — listed
in PAPERS.md retrieval set as the standard technique).

The online-softmax accumulation (running max ``m``, normalizer ``l``,
unnormalized output ``o``) makes each block's contribution exact, so the
result equals full attention bit-for-bit up to float associativity.

Compute/communication overlap: each step's K/V rotation is issued as the
same XLA program as the block matmuls; XLA schedules the ppermute
concurrently with compute (ICI DMA), which is the standard ring pipeline.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def _block(q, k, v, m, l, o, scale, mask):
    """One blockwise-attention accumulation step (online softmax).

    q: (B, H, Tq, D); k,v: (B, H, Tk, D); m,l: (B, H, Tq); o like q but f32.
    mask: (Tq, Tk) bool, True = attend."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # exp(-inf - -inf) guard: rows with no attendable keys yet keep m=-inf
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(mask, p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return m_new, l_new, o_new


def _ring_attn_local(q, k, v, *, axis_name: str, batch_axis: str,
                     causal: bool, scale: float):
    """Per-shard body (runs under shard_map).  q,k,v: (B, H, T_loc, D)
    local shards; sequence dim globally sharded over ``axis_name``."""
    p_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    B, H, T, D = q.shape

    # mark accumulators device-varying over every mesh axis the inputs are
    # sharded on, so the fori_loop carry types match (shard_map
    # varying-manual-axes check, jax >= 0.8)
    axes = (batch_axis, axis_name)
    m0 = lax.pcast(jnp.full((B, H, T), -jnp.inf, jnp.float32), axes,
                   to="varying")
    l0 = lax.pcast(jnp.zeros((B, H, T), jnp.float32), axes, to="varying")
    o0 = lax.pcast(jnp.zeros((B, H, T, D), jnp.float32), axes, to="varying")

    perm = [(i, (i + 1) % p_size) for i in range(p_size)]
    qpos = my_idx * T + jnp.arange(T)

    def attend(step, k_cur, v_cur, m, l, o):
        # K/V currently held came from shard (my_idx - step) mod p
        src = (my_idx - step) % p_size
        kpos = src * T + jnp.arange(T)
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
        else:
            mask = jnp.ones((T, T), bool)
        return _block(q, k_cur, v_cur, m, l, o, scale, mask)

    # step 0 attends to the local K/V; each later step rotates first —
    # p_size-1 rotations total, none wasted
    m, l, o = attend(0, k, v, m0, l0, o0)

    def body(step, carry):
        k_cur, v_cur, m, l, o = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        m, l, o = attend(step, k_cur, v_cur, m, l, o)
        return (k_cur, v_cur, m, l, o)

    if p_size > 1:
        _, _, m, l, o = lax.fori_loop(1, p_size, body, (k, v, m, l, o))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                   batch_axis: str = "data", causal: bool = False,
                   scale: Optional[float] = None):
    """Sequence-parallel attention.  q,k,v: (B, H, T, D) with T sharded
    over ``mesh[seq_axis]`` (batch may be sharded over ``batch_axis``)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(batch_axis, None, seq_axis, None)
    fn = shard_map(
        functools.partial(_ring_attn_local, axis_name=seq_axis,
                          batch_axis=batch_axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
