"""bigdl_tpu.parallel — mesh topology, explicit gradient sync
(AllReduceParameter analog), tensor parallelism, sequence parallelism
(ring attention).

Replaces the reference's distributed substrate (Spark BlockManager
AllReduce, ``DL/parameters/``) with XLA collectives over ICI —
``grad_sync`` is the explicit reduce-scatter/sharded-update/all-gather
wire-format protocol of ``AllReduceParameter.scala`` — and adds the
TP/SP strategies the reference lacks (SURVEY §2.9).
"""

from bigdl_tpu.parallel import grad_sync
from bigdl_tpu.parallel.grad_sync import (
    BucketPlan, build_plan, resolve_wire_dtype,
)
from bigdl_tpu.parallel.mesh import (
    create_mesh, data_sharding, replicated, mesh_shape,
)
from bigdl_tpu.parallel.ring_attention import ring_attention
from bigdl_tpu.parallel.tensor_parallel import (
    build_param_specs, column_parallel_linear_specs,
    row_parallel_linear_specs,
)
from bigdl_tpu.parallel.pipeline import (
    GPipe, MicrobatchedSequential, partition_sequential,
)
