"""Pipeline parallelism over the mesh's ``pipe`` axis (GPipe-style).

The reference has NO pipeline parallelism (SURVEY §2.9 — Spark-era BigDL
is pure data-parallel); this is a beyond-reference capability the TPU
build adds, filling the ``pipe`` mesh axis declared in ``parallel/mesh.py``.

TPU-idiomatic design (the scaling-book collective-permute recipe, not a
host-driven scheduler):

- **Stages are stacked**: a pipeline of S identical-structure stages keeps
  its parameters as one pytree with a leading ``(S, ...)`` axis, sharded
  over ``pipe`` — each device holds exactly its stage's slice (the PP
  memory win).
- **The schedule is one ``lax.scan`` inside ``shard_map``**: T = M + S - 1
  ticks for M microbatches.  Every tick each rank applies its stage to its
  current activation and the result is ``ppermute``d to rank+1 while rank
  0 ingests the next microbatch — all ranks stay busy after the S-1-tick
  fill.  Bubble fraction = (S-1)/T, amortized by M like GPipe.
- **Backward is just ``jax.grad``** through the scan + ppermute (both
  differentiable); no hand-written 1F1B machinery.

Heterogeneous ``Sequential`` models: :func:`partition_sequential` splits
layers into S balanced stage lists; those are only stackable when the
stages share a pytree structure (e.g. repeated blocks).  For arbitrary
stage structures use :class:`MicrobatchedSequential`, which reproduces
GPipe's exact math (microbatched loss == full-batch loss) without the
spatial placement — correctness path for the dryrun and small meshes.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Module, Sequential


# ------------------------------------------------------- stage partitioning
def partition_sequential(model: Sequential, num_stages: int
                         ) -> List[Sequential]:
    """Split a Sequential's children into ``num_stages`` balanced stages
    (by layer count).  Mirrors GPipe's per-device partitioning."""
    mods = list(model.modules)
    if num_stages <= 0 or num_stages > len(mods):
        raise ValueError(f"cannot split {len(mods)} layers into "
                         f"{num_stages} stages")
    sizes = [len(mods) // num_stages] * num_stages
    for i in range(len(mods) % num_stages):
        sizes[i] += 1
    stages, ix = [], 0
    for s in sizes:
        stages.append(Sequential(*mods[ix:ix + s]))
        ix += s
    return stages


# ------------------------------------------------------------ stacked GPipe
class GPipe(Module):
    """SPMD pipeline of S identical-structure stages.

    ``stage``: a Module whose ``apply(params, {}, x)`` maps activations to
    activations with the same pytree structure of params at every stage
    (e.g. one transformer block, one MLP block).  ``init`` stacks S
    independent initializations into leading-axis-S arrays; under a mesh
    the caller shards that axis over ``pipe``.

    ``apply`` expects input already split into microbatches:
    ``(M, mb, ...)``; it returns ``(M, mb, ...)`` outputs.
    """

    def __init__(self, stage: Module, num_stages: int,
                 mesh: Optional[Mesh] = None, axis: str = "pipe",
                 name: Optional[str] = None):
        super().__init__(name)
        self.stage = stage
        self.num_stages = num_stages
        self.mesh = mesh
        self.axis = axis

    def init(self, rng):
        ks = jax.random.split(rng, self.num_stages)
        inits = [self.stage.init(k) for k in ks]
        params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[p for p, _ in inits])
        # stages must be stateless under the pipelined schedule (BN running
        # stats would need per-stage state plumbing); keep the empty-state
        # template for stage_apply
        self._stage_state = inits[0][1]
        return params, {}

    def stage_sharding(self) -> NamedSharding:
        """Sharding that gives each pipe rank its stage slice."""
        assert self.mesh is not None
        return NamedSharding(self.mesh, P(self.axis))

    # pure single-device reference (for parity tests): sequential stages
    def apply_reference(self, params, x):
        M = x.shape[0]
        out = x.reshape((-1,) + x.shape[2:])
        st = getattr(self, "_stage_state", {})
        for s in range(self.num_stages):
            p_s = jax.tree_util.tree_map(lambda a, s=s: a[s], params)
            out, _ = self.stage.apply(p_s, st, out)
        return out.reshape((M,) + x.shape[1:])

    def apply(self, params, state, input, *, training=False, rng=None):
        """Microbatched pipelined forward under shard_map.

        input: (M, mb, ...) microbatches. Requires a mesh whose
        ``self.axis`` size == num_stages."""
        if self.mesh is None:
            return self.apply_reference(params, input), state
        S, axis = self.num_stages, self.axis
        M = input.shape[0]
        stage_apply = self.stage.apply
        stage_state = getattr(self, "_stage_state", {})

        def pipeline_rank(p_stage, xs):
            # p_stage: this rank's stage params (leading axis 1); xs: all
            # microbatches (replicated feed; rank 0 consumes them)
            p = jax.tree_util.tree_map(lambda a: a[0], p_stage)
            rank = lax.axis_index(axis)
            T = M + S - 1
            buf = jnp.zeros_like(xs[0])          # current activation
            outs = jnp.zeros_like(xs)            # collected at last rank

            def tick(carry, t):
                buf, outs = carry
                # rank 0 ingests microbatch t (older ranks keep piped data)
                feed = xs[jnp.minimum(t, M - 1)]
                x_in = jnp.where(rank == 0, feed, buf)
                y, _ = stage_apply(p, stage_state, x_in)
                # send to next rank; ring wraps, rank 0's incoming is unused
                y_next = lax.ppermute(
                    y, axis, [(i, (i + 1) % S) for i in range(S)])
                # last rank finished microbatch t-(S-1) at tick t
                done_ix = t - (S - 1)
                is_done = (rank == S - 1) & (done_ix >= 0)
                outs = lax.cond(
                    is_done,
                    lambda o: o.at[jnp.maximum(done_ix, 0)].set(y),
                    lambda o: o, outs)
                return (y_next, outs), None

            (buf, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(T))
            # broadcast results from the last rank to all (psum of one-hot)
            outs = lax.psum(
                jnp.where(rank == S - 1, outs, jnp.zeros_like(outs)), axis)
            return outs

        from jax.experimental.shard_map import shard_map
        fn = shard_map(
            pipeline_rank, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(self.axis), params),
                      P()),
            out_specs=P(),
            check_rep=False)
        return fn(params, input), state


class MicrobatchedSequential(Module):
    """GPipe math without spatial placement: run each microbatch through
    heterogeneous stages sequentially and concatenate.  For stateless
    layers the recombined output is bit-identical to the unpipelined
    model; stateful layers (BatchNorm) see the microbatches sequentially —
    state is threaded microbatch-to-microbatch, so running statistics
    advance once per microbatch (M small-batch updates, the standard
    microbatching semantics, not one full-batch update)."""

    def __init__(self, stages: Sequence[Module],
                 num_microbatches: int, name: Optional[str] = None):
        super().__init__(name)
        self.stages = list(stages)
        self.num_microbatches = num_microbatches

    def spec_children(self):
        return {str(i): m for i, m in enumerate(self.stages)}

    def init(self, rng):
        params, state = {}, {}
        for i, m in enumerate(self.stages):
            rng, sub = jax.random.split(rng)
            p, s = m.init(sub)
            params[str(i)] = p
            state[str(i)] = s
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        N = input.shape[0]
        M = self.num_microbatches
        if N % M:
            raise ValueError(f"batch {N} not divisible into {M} microbatches")
        mbs = input.reshape((M, N // M) + input.shape[1:])

        def run_one(x, cur_state):
            new_state = {}
            for i, m in enumerate(self.stages):
                x, s = m.apply(params[str(i)], cur_state[str(i)], x,
                               training=training)
                new_state[str(i)] = s
            return x, new_state

        outs = []
        cur = state  # thread state through microbatches (BN running stats
        # advance per microbatch instead of keeping only the last update)
        for i in range(M):
            o, cur = run_one(mbs[i], cur)
            outs.append(o)
        outs = jnp.stack(outs)
        return outs.reshape((N,) + outs.shape[2:]), cur
