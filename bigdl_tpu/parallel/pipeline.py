"""Pipeline parallelism over the mesh's ``pipe`` axis (GPipe-style).

The reference has NO pipeline parallelism (SURVEY §2.9 — Spark-era BigDL
is pure data-parallel); this is a beyond-reference capability the TPU
build adds, filling the ``pipe`` mesh axis declared in ``parallel/mesh.py``.

TPU-idiomatic design (the scaling-book collective-permute recipe, not a
host-driven scheduler):

- **Stages are stacked**: a pipeline of S identical-structure stages keeps
  its parameters as one pytree with a leading ``(S, ...)`` axis, sharded
  over ``pipe`` — each device holds exactly its stage's slice (the PP
  memory win).
- **The schedule is one ``lax.scan`` inside ``shard_map``**: T = M + S - 1
  ticks for M microbatches.  Every tick each rank applies its stage to its
  current activation and the result is ``ppermute``d to rank+1 while rank
  0 ingests the next microbatch — all ranks stay busy after the S-1-tick
  fill.  Bubble fraction = (S-1)/T, amortized by M like GPipe.
- **Backward is just ``jax.grad``** through the scan + ppermute (both
  differentiable); no hand-written 1F1B machinery.

Heterogeneous ``Sequential`` models: :func:`partition_sequential` splits
layers into S balanced stage lists; those are only stackable when the
stages share a pytree structure (e.g. repeated blocks).  For arbitrary
stage structures use :class:`MicrobatchedSequential`, which reproduces
GPipe's exact math (microbatched loss == full-batch loss) without the
spatial placement — correctness path for the dryrun and small meshes.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Module, Sequential


# ------------------------------------------------------- stage partitioning
def partition_sequential(model: Sequential, num_stages: int
                         ) -> List[Sequential]:
    """Split a Sequential's children into ``num_stages`` balanced stages
    (by layer count).  Mirrors GPipe's per-device partitioning."""
    mods = list(model.modules)
    if num_stages <= 0 or num_stages > len(mods):
        raise ValueError(f"cannot split {len(mods)} layers into "
                         f"{num_stages} stages")
    sizes = [len(mods) // num_stages] * num_stages
    for i in range(len(mods) % num_stages):
        sizes[i] += 1
    stages, ix = [], 0
    for s in sizes:
        stages.append(Sequential(*mods[ix:ix + s]))
        ix += s
    return stages


# ------------------------------------------------------------ stacked GPipe
class GPipe(Module):
    """SPMD pipeline of S identical-structure stages.

    ``stage``: a Module whose ``apply(params, {}, x)`` maps activations to
    activations with the same pytree structure of params at every stage
    (e.g. one transformer block, one MLP block).  ``init`` stacks S
    independent initializations into leading-axis-S arrays; under a mesh
    the caller shards that axis over ``pipe``.

    ``apply`` expects input already split into microbatches:
    ``(M, mb, ...)``; it returns ``(M, mb, ...)`` outputs.
    """

    def __init__(self, stage: Module, num_stages: int,
                 mesh: Optional[Mesh] = None, axis: str = "pipe",
                 name: Optional[str] = None):
        super().__init__(name)
        self.stage = stage
        self.num_stages = num_stages
        self.mesh = mesh
        self.axis = axis
        # eager state-template capture: the pipelined schedule needs the
        # stage's static state STRUCTURE even when the caller threads no
        # state; computing it at construction keeps apply() free of
        # host-side memo writes inside a traced scope
        _, self._state_template = stage.init(jax.random.PRNGKey(0))

    def init(self, rng):
        ks = jax.random.split(rng, self.num_stages)
        inits = [self.stage.init(k) for k in ks]
        params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[p for p, _ in inits])
        # per-stage STATE is stacked the same way (leading S axis) and
        # threaded through the pipelined schedule — BN running stats work
        state = {}
        if jax.tree_util.tree_leaves(inits[0][1]):
            state = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *[s for _, s in inits])
        self._state_template = inits[0][1]
        return params, state

    def stage_sharding(self) -> NamedSharding:
        """Sharding that gives each pipe rank its stage slice."""
        assert self.mesh is not None
        return NamedSharding(self.mesh, P(self.axis))

    def _template(self):
        return self._state_template

    # pure single-device reference (for parity tests): sequential stages
    def apply_reference(self, params, state, x, *, training=False):
        M = x.shape[0]
        has_state = bool(jax.tree_util.tree_leaves(state))
        out = x.reshape((-1,) + x.shape[2:])
        new_states = []
        for s in range(self.num_stages):
            p_s = jax.tree_util.tree_map(lambda a, s=s: a[s], params)
            st_s = jax.tree_util.tree_map(lambda a, s=s: a[s], state) \
                if has_state else self._template()
            out, ns = self.stage.apply(p_s, st_s, out, training=training)
            new_states.append(ns)
        if has_state:
            state = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                           *new_states)
        return out.reshape((M,) + x.shape[1:]), state

    def apply(self, params, state, input, *, training=False, rng=None):
        """Microbatched pipelined forward under shard_map.

        input: (M, mb, ...) microbatches with M divisible by S; the
        microbatch axis is SHARDED over ``pipe`` (each rank holds M/S
        microbatches — no replicated O(M·mb) feed), and outputs come
        back the same way.  Requires a mesh whose ``self.axis`` size ==
        num_stages."""
        if self.mesh is None:
            return self.apply_reference(params, state, input,
                                        training=training)
        S, axis = self.num_stages, self.axis
        M = input.shape[0]
        if M % S:
            raise ValueError(f"microbatch count {M} must divide by "
                             f"pipeline stages {S}")
        chunk = M // S
        stage_apply = self.stage.apply
        has_state = bool(jax.tree_util.tree_leaves(state))
        template = self._template()

        def pipeline_rank(p_stage, st_stage, xs_local):
            # p_stage/st_stage: this rank's stage slice (leading axis 1);
            # xs_local: this rank's (M/S, mb, ...) chunk of the feed
            p = jax.tree_util.tree_map(lambda a: a[0], p_stage)
            st = jax.tree_util.tree_map(lambda a: a[0], st_stage) \
                if has_state else template
            rank = lax.axis_index(axis)
            T = M + S - 1
            buf = jnp.zeros_like(xs_local[0])     # current activation
            outs = jnp.zeros_like(xs_local)       # this rank's output chunk

            def tick(carry, t):
                buf, outs, st = carry
                # the owner of microbatch t contributes it; psum of the
                # one-hot contribution = distributed queue pop for rank 0
                owner = t // chunk
                local_ix = jnp.clip(t - rank * chunk, 0, chunk - 1)
                mine = jnp.where(rank == owner, xs_local[local_ix],
                                 jnp.zeros_like(xs_local[local_ix]))
                feed = lax.psum(mine, axis)
                x_in = jnp.where(rank == 0, feed, buf)
                y, st_new = stage_apply(p, st, x_in, training=training)
                # this rank's stage sees VALID data only for ticks
                # rank <= t < rank+M: freeze state updates on bubbles
                # (fill/drain garbage must not pollute BN stats)
                valid = (t >= rank) & (t < rank + M)
                if has_state:
                    st = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(valid, new, old),
                        st_new, st)
                # send to next rank; ring wraps, rank 0's incoming unused
                y_next = lax.ppermute(
                    y, axis, [(i, (i + 1) % S) for i in range(S)])
                # last rank finished microbatch t-(S-1) at tick t: route
                # it to the OWNING rank's output chunk (psum one-hot)
                done_ix = t - (S - 1)
                done = jnp.where((rank == S - 1) & (done_ix >= 0), y, 0.0)
                done = lax.psum(done, axis)
                out_owner = jnp.maximum(done_ix, 0) // chunk
                out_local = jnp.clip(done_ix - rank * chunk, 0, chunk - 1)
                write = (done_ix >= 0) & (out_owner == rank)
                outs = lax.cond(
                    write,
                    lambda o: o.at[out_local].set(done),
                    lambda o: o, outs)
                return (y_next, outs, st), None

            (buf, outs, st), _ = lax.scan(tick, (buf, outs, st),
                                          jnp.arange(T))
            st_out = jax.tree_util.tree_map(lambda a: a[None], st) \
                if has_state else {}
            return outs, st_out

        try:
            from jax import shard_map  # jax >= 0.8 (check_rep renamed)
            kw = {"check_vma": False}
        except ImportError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map
            kw = {"check_rep": False}
        fn = shard_map(
            pipeline_rank, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(self.axis), params),
                      jax.tree_util.tree_map(lambda _: P(self.axis), state),
                      P(self.axis)),
            out_specs=(P(self.axis),
                       jax.tree_util.tree_map(lambda _: P(self.axis),
                                              state)),
            **kw)
        outs, new_state = fn(params, state, input)
        return outs, new_state


class MicrobatchedSequential(Module):
    """GPipe math without spatial placement: run each microbatch through
    heterogeneous stages sequentially and concatenate.  For stateless
    layers the recombined output is bit-identical to the unpipelined
    model; stateful layers (BatchNorm) see the microbatches sequentially —
    state is threaded microbatch-to-microbatch, so running statistics
    advance once per microbatch (M small-batch updates, the standard
    microbatching semantics, not one full-batch update)."""

    def __init__(self, stages: Sequence[Module],
                 num_microbatches: int, name: Optional[str] = None):
        super().__init__(name)
        self.stages = list(stages)
        self.num_microbatches = num_microbatches

    def spec_children(self):
        return {str(i): m for i, m in enumerate(self.stages)}

    def init(self, rng):
        params, state = {}, {}
        for i, m in enumerate(self.stages):
            rng, sub = jax.random.split(rng)
            p, s = m.init(sub)
            params[str(i)] = p
            state[str(i)] = s
        return params, state

    def apply(self, params, state, input, *, training=False, rng=None):
        N = input.shape[0]
        M = self.num_microbatches
        if N % M:
            raise ValueError(f"batch {N} not divisible into {M} microbatches")
        mbs = input.reshape((M, N // M) + input.shape[1:])

        def run_one(x, cur_state):
            new_state = {}
            for i, m in enumerate(self.stages):
                x, s = m.apply(params[str(i)], cur_state[str(i)], x,
                               training=training)
                new_state[str(i)] = s
            return x, new_state

        outs = []
        cur = state  # thread state through microbatches (BN running stats
        # advance per microbatch instead of keeping only the last update)
        for i in range(M):
            o, cur = run_one(mbs[i], cur)
            outs.append(o)
        outs = jnp.stack(outs)
        return outs.reshape((N,) + outs.shape[2:]), cur
