"""Compressed, bucketed gradient synchronization — the TPU-native
``AllReduceParameter``.

Reference: ``DL/parameters/AllReduceParameter.scala`` +
``FP16CompressedTensor.scala``.  Each Spark iteration, every node (1)
fetches the FP16-compressed gradient partitions of its owned 1/N slice
of the flat parameter vector, (2) aggregates them and runs the
optimizer on that slice only, and (3) re-publishes the updated slice in
the FP16 wire format for the next forward.  That protocol IS a
reduce-scatter (+ sharded update) + all-gather with a compressed wire
dtype — see also "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv:2004.13336), the same design expressed
in XLA terms.

The first TPU port dropped the wire format on the assumption that ICI
makes software compression unnecessary; BENCH r05 then measured
``collective_overhead_fraction = 0.32`` at 8 chips — gradient sync, not
compute, was the biggest gap.  This module brings the explicit protocol
back, natively:

- gradients are flattened into **size-capped buckets**
  (``Config.grad_bucket_bytes``) so XLA's latency-hiding scheduler can
  overlap per-bucket collectives with backward compute instead of
  waiting for one monolithic fused all-reduce;
- each bucket is **downcast to the wire dtype**
  (``Config.grad_wire_dtype``: f32 | bf16 | f16) with the shared
  unbiased rounding (``utils.precision.stochastic_round`` — the same
  helper behind SGD's reduced-precision momentum), then
  ``lax.psum_scatter`` over the ``data`` axis hands every chip its
  owned 1/N slice, upcast to f32;
- the optimizer update runs on the **f32 master slice** each chip owns
  (``gs_state["master"]``) — ZeRO-1 exactly, subsuming the old
  constraint-only sharded-state path;
- updated slices are downcast to the wire dtype and ``lax.all_gather``-ed
  back into the replicated f32 param pytree used by the next
  forward/backward (the analog of the reference's FP16 weight
  re-publish: with a sub-f32 wire the replicated params carry wire
  precision, the per-chip masters stay exact f32).

Everything runs inside ``shard_map`` within the fused K-step jit built
by ``DistriOptimizer._build_block_fn``; this module holds the pure
per-chip math plus the host-side bucket planning.

Semantics vs the GSPMD auto-collective path (documented divergences,
all shared with the reference's per-executor training):
- the loss reported is the pmean of per-chip local-batch means
  (identical for equal shard sizes, up to float association);
- batch-statistics layers (BatchNorm) see their LOCAL batch shard; the
  new model state is pmean-synced across chips after the step (the
  reference computes per-partition statistics the same way);
- dropout draws the same per-step key on every chip, applied to that
  chip's batch shard.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.utils.precision import stochastic_round

tmap = jax.tree_util.tree_map

# wire-dtype knob values (Config.grad_wire_dtype / DistriOptimizer
# grad_wire_dtype=...); f32 is the identity wire — bitwise-equal to a
# plain psum, gated by tests/test_grad_sync.py
WIRE_DTYPES = {
    "f32": jnp.float32, "float32": jnp.float32,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "f16": jnp.float16, "float16": jnp.float16,
}

# base key for the wire downcast noise; per-(step, bucket) keys are
# folded in so no two downcasts in a block share noise
_WIRE_KEY_SALT = 0x77e1


def resolve_wire_dtype(name) -> Any:
    """``"bf16"``/``"f32"``/``"f16"`` (or a jnp dtype) → jnp dtype."""
    if not isinstance(name, str):
        return jnp.dtype(name).type if name is not None else jnp.float32
    try:
        return WIRE_DTYPES[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown grad wire dtype {name!r}; expected one of "
            f"{sorted(set(WIRE_DTYPES))}") from None


class BucketPlan:
    """Static flattening plan: which param leaves land in which bucket,
    at what offset, and how much tail padding makes each bucket divide
    evenly over the ``data`` axis.  Built once per run on the host —
    everything jit-traced closes over it as a constant."""

    __slots__ = ("n_shard", "leaf_meta", "buckets", "bucket_sizes",
                 "treedef")

    def __init__(self, n_shard: int, leaf_meta, buckets, bucket_sizes,
                 treedef):
        self.n_shard = n_shard
        self.leaf_meta = leaf_meta        # [(shape, size, dtype)]
        self.buckets = buckets            # [[leaf index, ...], ...]
        self.bucket_sizes = bucket_sizes  # padded, % n_shard == 0
        self.treedef = treedef

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def slice_size(self, b: int) -> int:
        return self.bucket_sizes[b] // self.n_shard


def build_plan(params, n_shard: int, bucket_bytes: int) -> BucketPlan:
    """Greedy size-capped bucketing in leaf order.  A leaf larger than
    the cap gets a bucket of its own (never split — slicing a single
    leaf across buckets would complicate unflattening for no overlap
    benefit: one oversized bucket is already one collective)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise ValueError("grad_sync: model has no parameters")
    leaf_meta = [(tuple(l.shape), int(np.prod(l.shape, dtype=np.int64)),
                  jnp.dtype(l.dtype)) for l in leaves]
    cap = max(1, int(bucket_bytes) // 4)  # f32 elements per bucket
    buckets: List[List[int]] = []
    sizes: List[int] = []
    cur: List[int] = []
    cur_n = 0
    for i, (_, size, _) in enumerate(leaf_meta):
        # bucketing is a pure function of the param tree and
        # grad_bucket_bytes — every host derives the identical plan
        # (and therefore the identical collective schedule)
        # replicated-by: plan-from-config
        if cur and cur_n + size > cap:
            buckets.append(cur)
            sizes.append(cur_n)
            cur, cur_n = [], 0
        cur.append(i)
        cur_n += size
    buckets.append(cur)
    sizes.append(cur_n)
    padded = [-(-s // n_shard) * n_shard for s in sizes]
    return BucketPlan(n_shard, leaf_meta, buckets, padded, treedef)


def flatten_to_buckets(plan: BucketPlan, tree) -> List[jnp.ndarray]:
    """Pytree → list of padded flat f32 buckets (leaf order, zeros in
    the tail padding)."""
    leaves = jax.tree_util.tree_leaves(tree)
    out = []
    for b, idxs in enumerate(plan.buckets):
        flat = jnp.concatenate(
            [leaves[i].reshape(-1).astype(jnp.float32) for i in idxs])
        pad = plan.bucket_sizes[b] - flat.shape[0]
        if pad:  # replicated-by: plan-from-config
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        out.append(flat)
    return out


def unflatten_from_buckets(plan: BucketPlan, buckets: Sequence):
    """Inverse of :func:`flatten_to_buckets` — original shapes/dtypes."""
    leaves: List[Optional[jnp.ndarray]] = [None] * len(plan.leaf_meta)
    for b, idxs in enumerate(plan.buckets):
        off = 0
        flat = buckets[b]
        for i in idxs:
            shape, size, dtype = plan.leaf_meta[i]
            leaves[i] = lax.slice(flat, (off,), (off + size,)) \
                .reshape(shape).astype(dtype)
            off += size
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


def init_state(plan: BucketPlan, params, optim_method) -> dict:
    """Build the grad_sync optimizer-state pytree: f32 master buckets
    (the full flat vectors — placing them with a ``P("data")`` sharding
    gives each chip exactly its owned slice) plus the wrapped
    optimizer's own state over those buckets.

    Only elementwise (tree-map-shaped) optimizers qualify: each inner
    state leaf must mirror a master bucket leaf-for-leaf so the
    host-built full-bucket state shards into per-chip slice state.
    L-BFGS (flat history matrices) does not — it needs the full
    vector on every chip."""
    masters = flatten_to_buckets(plan, params)
    inner = optim_method.init_state(masters)
    master_shapes = {m.shape for m in masters}
    for leaf in jax.tree_util.tree_leaves(inner):
        # model structure is identical on every host — the refusal (or
        # not) is uniform  # replicated-by: model-structure
        if leaf.shape not in master_shapes:
            raise ValueError(
                f"grad_sync requires an elementwise optimizer whose "
                f"state leaves mirror the parameter buckets; "
                f"{type(optim_method).__name__} created a "
                f"{leaf.shape}-shaped state leaf (buckets: "
                f"{sorted(master_shapes)}).  Use parameter_sharding="
                f"False/grad_sync=False for this method.")
    return {"master": masters, "opt": inner}


def bucket_content_sizes(plan: BucketPlan) -> List[int]:
    """Unpadded element count of each bucket — a pure function of the
    param tree and ``grad_bucket_bytes``, INVARIANT under the world
    size (only the tail padding divides by ``n_shard``).  This is the
    quantity elastic resume compares across snapshots: two plans with
    equal content layouts hold the same logical values, however they
    were padded."""
    return [sum(plan.leaf_meta[i][1] for i in idxs)
            for idxs in plan.buckets]


def reshard_state(plan: BucketPlan, gs_state: dict) -> dict:
    """Re-pad a grad_sync optimizer state for a NEW world size
    (elastic resume).  Runs on the host against the freshly-restored
    state: every array leaf of ``gs_state`` is a padded flat bucket
    (masters and elementwise inner state alike — ``init_state``
    enforces the mirror), identified by the trailing list index of its
    tree path.  Padding carries no information (``flatten_to_buckets``
    zero-fills, elementwise optimizers map zeros to zeros), so
    resharding is: slice each bucket to its content, re-pad with zeros
    to ``plan.bucket_sizes``.  Gradient sums are world-size-invariant,
    making the resharded trajectory exact at the replay boundary."""
    content = bucket_content_sizes(plan)

    def _bucket_ix(path) -> int:
        for entry in reversed(path):
            if isinstance(entry, jax.tree_util.SequenceKey):
                return entry.idx
        key = jax.tree_util.keystr(path)
        raise ValueError(
            f"grad_sync reshard: state leaf at {key} has no bucket "
            f"index — not a grad_sync state layout")

    def _repad(path, leaf):
        b = _bucket_ix(path)
        if b >= len(content):  # replicated-by: plan-from-config
            raise ValueError(
                f"grad_sync reshard: state has a bucket #{b} but the "
                f"new plan only has {plan.num_buckets} — param tree or "
                f"grad_bucket_bytes changed, not just the world size")
        arr = np.asarray(leaf)
        # every host restored the same snapshot — its bucket layout is
        # uniform  # replicated-by: snapshot-schema
        if arr.ndim != 1 or arr.shape[0] < content[b]:
            raise ValueError(
                f"grad_sync reshard: bucket #{b} holds "
                f"{arr.shape} elements but the plan needs "
                f"{content[b]} — param tree or grad_bucket_bytes "
                f"changed, not just the world size")
        out = np.zeros((plan.bucket_sizes[b],), dtype=arr.dtype)
        out[:content[b]] = arr[:content[b]]
        return out

    return jax.tree_util.tree_map_with_path(_repad, gs_state)


def wire_cast(x, wire_dtype, key, n_sum: int = 1):
    """Downcast one bucket to the wire dtype with the shared unbiased
    rounding (no-op for the f32 wire).  The f16 wire SATURATES first:
    unlike bf16 (f32 exponent range, no loss scaling needed), an f16
    wire can overflow to inf and poison the masters with NaN via the
    psum.  ``n_sum`` is the number of such values the collective will
    SUM downstream — each chip's contribution clamps to ±(65504 /
    n_sum) so even a coherent worst-case spike across all chips stays
    finite through the f16 accumulation (pre-reduction values merely
    within range are not enough).  Clamping trades silent divergence
    for a bounded, clipping-like bias on the rare overflowing element,
    the same behavior as NCCL-style fp16 rings."""
    wd = jnp.dtype(wire_dtype)
    if wd == jnp.float32:  # replicated-by: config-derived
        return x
    if wd == jnp.float16:
        lim = float(jnp.finfo(jnp.float16).max) / max(1, int(n_sum))
        x = jnp.clip(x, -lim, lim)
    return stochastic_round(x, wire_dtype, key)


def _wire_key(step, tag: int):
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(_WIRE_KEY_SALT), step), tag)


def reduce_scatter_grads(plan: BucketPlan, grads, *, wire_dtype,
                         axis_name: str, step) -> List[jnp.ndarray]:
    """Local grad pytree → list of owned f32 slices of the global MEAN
    gradient.  The 1/n pre-scale implements the pmean convention (each
    chip differentiates its local-batch-mean loss); for power-of-two
    meshes the scale is exact, so the f32 wire stays bitwise-equal to
    psum-then-divide."""
    n = plan.n_shard
    # fold the chip index into the downcast key: per-chip grads are
    # SIMILAR in DP, so a shared noise pattern would round the same
    # direction on every chip and the rounding errors would sum
    # coherently (~n·ε) in the psum_scatter instead of canceling
    # (~√n·ε) as independent noise does
    chip = lax.axis_index(axis_name)
    owned = []
    for b, flat in enumerate(flatten_to_buckets(plan, grads)):
        key = jax.random.fold_in(_wire_key(step, b), chip)
        w = wire_cast(flat / n, wire_dtype, key, n_sum=n)
        o = lax.psum_scatter(w, axis_name, scatter_dimension=0, tiled=True)
        owned.append(o.astype(jnp.float32))
    return owned


def all_gather_params(plan: BucketPlan, masters, *, wire_dtype,
                      axis_name: str, step):
    """Owned f32 master slices → replicated f32 param pytree via the
    wire dtype (the FP16 weight re-publish of the reference: replicated
    params carry wire precision; masters stay exact)."""
    gathered = []
    for b, mslice in enumerate(masters):
        w = wire_cast(mslice, wire_dtype,
                      _wire_key(step, plan.num_buckets + b))
        g = lax.all_gather(w, axis_name, axis=0, tiled=True)
        gathered.append(g.astype(jnp.float32))
    return unflatten_from_buckets(plan, gathered)


def clip_slices(owned: List[jnp.ndarray], clip_spec, axis_name: str):
    """Gradient clipping on the owned slices of the REDUCED gradient —
    semantically identical to clipping the full psum'd gradient:
    value-clip is elementwise; the global L2 norm is the psum of
    per-slice square sums (the slices partition the flat vector)."""
    if clip_spec is None:
        return owned
    kind = clip_spec[0]
    if kind == "value":
        _, lo, hi = clip_spec
        return [jnp.clip(o, lo, hi) for o in owned]
    if kind == "norm":
        _, max_norm = clip_spec
        local_sq = sum(jnp.sum(o.astype(jnp.float32) ** 2) for o in owned)
        norm = jnp.sqrt(lax.psum(local_sq, axis_name))
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
        return [o * scale for o in owned]
    raise ValueError(f"unknown clip spec {clip_spec!r}")


def sync_and_update(plan: BucketPlan, grads, gs_state: dict, optim_method,
                    lr, step, *, wire_dtype, axis_name: str = "data",
                    clip_spec=None) -> Tuple[Any, dict]:
    """One full AllReduceParameter round on-device (inside shard_map):
    reduce-scatter compressed grads → clip → optimizer update on the
    owned slice → all-gather compressed params.  Returns the new
    replicated param pytree and the new grad_sync state."""
    owned = reduce_scatter_grads(plan, grads, wire_dtype=wire_dtype,
                                 axis_name=axis_name, step=step)
    owned = clip_slices(owned, clip_spec, axis_name)
    masters, inner = optim_method.update(
        owned, gs_state["master"], gs_state["opt"], lr, step)
    params = all_gather_params(plan, masters, wire_dtype=wire_dtype,
                               axis_name=axis_name, step=step)
    return params, {"master": masters, "opt": inner}


def sync_model_state(mstate, axis_name: str):
    """pmean the floating leaves of the post-step model state so the
    replicated out-spec is truthful (BatchNorm running stats become the
    cross-chip average of per-shard statistics — per-partition stats,
    like the reference); integer/bool leaves (counters) advance
    identically on every chip and pass through."""
    return tmap(
        lambda a: lax.pmean(a, axis_name)
        if jnp.issubdtype(a.dtype, jnp.floating) else a,
        mstate)


def shard_map_compat(f, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions, with replication checking off
    (grad_sync outputs are replicated by construction — psum/pmean/
    all-gather — which the static checker cannot always prove)."""
    try:
        from jax import shard_map  # jax >= 0.8 (check_rep renamed)
        kw = {"check_vma": False}
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
        kw = {"check_rep": False}
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **kw)
