"""Mesh utilities — the topology layer of the distributed design.

Reference analog: the Spark driver/executor topology (``Engine.scala`` node
and core counts, ``SparkExtension``/BlockManager placement).  On TPU the
topology is a named ``jax.sharding.Mesh``; everything else (which collective
runs where) falls out of sharding annotations.

Axis conventions used across the framework:
- ``data``  — data parallelism (batch dim; gradients all-reduce over it)
- ``model`` — tensor/model parallelism (Megatron-style column/row splits)
- ``seq``   — sequence/context parallelism (ring attention)
- ``pipe``  — pipeline stages
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def create_mesh(data: int = -1, model: int = 1, seq: int = 1,
                pipe: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh over the devices.  ``data=-1`` absorbs whatever
    is left after the explicit axes."""
    devs = list(jax.devices()) if devices is None else list(devices)
    n = len(devs)
    fixed = model * seq * pipe
    if data == -1:
        assert n % fixed == 0, f"{n} devices not divisible by {fixed}"
        data = n // fixed
    total = data * fixed
    assert total <= n, f"mesh needs {total} devices, have {n}"
    arr = np.array(devs[:total]).reshape(data, model, seq, pipe)
    return Mesh(arr, axis_names=("data", "model", "seq", "pipe"))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dim sharded over every data-ish axis (batch rides data;
    seq-parallel attention additionally shards dim 1)."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_shape(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
