"""Engine — runtime/topology bookkeeping.

TPU-native analog of the reference's ``DL/utils/Engine.scala`` (553 LoC):
there, ``Engine.init`` parses Spark conf, sizes thread pools and records
node/core counts; every layer then calls ``Engine.default.invokeAndWait``
for intra-node parallelism.

On TPU none of that exists: intra-chip parallelism is XLA's job and
inter-chip parallelism is a ``jax.sharding.Mesh``.  What remains of the
Engine's role is topology bookkeeping — how many devices/hosts there are,
which mesh the optimizers should shard over — plus the ``bigdl.*``-style
config surface (reference: ``Engine.scala:45-47,190-215``), centralized
here as documented attributes instead of scattered system properties.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


def _default_retry_times() -> int:
    from bigdl_tpu.utils.config import get_config
    return get_config().failure_retry_times


def _default_steps_per_dispatch() -> int:
    from bigdl_tpu.utils.config import get_config
    return get_config().steps_per_dispatch


@dataclass
class _EngineState:
    initialized: bool = False
    mesh: Optional[Mesh] = None
    seed: int = 1
    # reference knob: bigdl.failure.retryTimes (DistriOptimizer retry
    # loop); default flows from the unified typed config
    # (utils/config.Config.failure_retry_times, env BIGDL_TPU_*)
    failure_retry_times: int = field(default_factory=_default_retry_times)
    # K-step dispatch fusion for the training driver loop (config
    # steps_per_dispatch / env BIGDL_TPU_STEPS_PER_DISPATCH); optimizers
    # resolve it here unless overridden per-run via
    # Optimizer.set_steps_per_dispatch
    steps_per_dispatch: int = field(
        default_factory=_default_steps_per_dispatch)


class Engine:
    """Process-wide runtime state.  ``Engine.init()`` is idempotent.

    Reference parity: ``Engine.init`` (``DL/utils/Engine.scala:105-118``),
    ``Engine.nodeNumber()/coreNumber()`` → :meth:`node_number` /
    :meth:`core_number` report JAX process/device counts instead of Spark
    executors/cores.
    """

    _state = _EngineState()

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def init(cls, seed: int = 1) -> None:
        cls._state.initialized = True
        cls._state.seed = seed

    @classmethod
    def is_initialized(cls) -> bool:
        return cls._state.initialized

    @classmethod
    def reset(cls) -> None:
        cls._state = _EngineState()

    # -- topology ----------------------------------------------------------
    @classmethod
    def node_number(cls) -> int:
        """Number of hosts (reference: Spark executor count)."""
        return jax.process_count()

    @classmethod
    def core_number(cls) -> int:
        """Devices per host (reference: cores per executor)."""
        return jax.local_device_count()

    @classmethod
    def device_count(cls) -> int:
        return jax.device_count()

    # -- mesh --------------------------------------------------------------
    @classmethod
    def set_mesh(cls, mesh: Mesh) -> None:
        cls._state.mesh = mesh

    @classmethod
    def get_mesh(cls) -> Mesh:
        """The mesh distributed optimizers shard over.

        Defaults to a 1-D data-parallel mesh over all devices — the direct
        analog of the reference's one-replica-per-core data parallelism
        (``DistriOptimizer.scala:136-139``), minus the per-core replication
        (the batch is sharded over devices instead).
        """
        if cls._state.mesh is None:
            devs = np.array(jax.devices())
            cls._state.mesh = Mesh(devs, axis_names=("data",))
        return cls._state.mesh

    # -- config ------------------------------------------------------------
    @classmethod
    def seed(cls) -> int:
        return cls._state.seed

    @classmethod
    def steps_per_dispatch(cls) -> int:
        """How many train steps the driver fuses into one jit dispatch."""
        return max(1, int(cls._state.steps_per_dispatch))

    @classmethod
    def set_steps_per_dispatch(cls, k: int) -> None:
        if int(k) < 1:
            raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
        cls._state.steps_per_dispatch = int(k)
