"""Engine — runtime/topology bookkeeping.

TPU-native analog of the reference's ``DL/utils/Engine.scala`` (553 LoC):
there, ``Engine.init`` parses Spark conf, sizes thread pools and records
node/core counts; every layer then calls ``Engine.default.invokeAndWait``
for intra-node parallelism.

On TPU none of that exists: intra-chip parallelism is XLA's job and
inter-chip parallelism is a ``jax.sharding.Mesh``.  What remains of the
Engine's role is topology bookkeeping — how many devices/hosts there are,
which mesh the optimizers should shard over — plus the ``bigdl.*``-style
config surface (reference: ``Engine.scala:45-47,190-215``), centralized
here as documented attributes instead of scattered system properties.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


def _default_retry_times() -> int:
    from bigdl_tpu.utils.config import get_config
    return get_config().failure_retry_times


@dataclass
class _EngineState:
    initialized: bool = False
    mesh: Optional[Mesh] = None
    seed: int = 1
    # reference knob: bigdl.failure.retryTimes (DistriOptimizer retry
    # loop); default flows from the unified typed config
    # (utils/config.Config.failure_retry_times, env BIGDL_TPU_*)
    failure_retry_times: int = field(default_factory=_default_retry_times)
    # K-step dispatch fusion for the training driver loop.  None =
    # never explicitly set at the Engine level: steps_per_dispatch()
    # then resolves through the default chain (configure()/env >
    # tuned_configs.json for the workload > Config dataclass default);
    # Engine.set_steps_per_dispatch pins an explicit process-wide value
    steps_per_dispatch: Optional[int] = None
    # custom-kernel selection (ops/pallas_*.py): "auto" | "pallas" |
    # "xla"; None = unset, resolved through the same default chain
    kernel_impl: Optional[str] = None
    # process-wide workload tag (Engine.set_workload): the key tuned
    # defaults are looked up under when a call site doesn't carry its
    # own tag (layer construction resolving kernel_impl, for example)
    workload: Optional[str] = None
    # whether Engine.set_xla_async_collectives has armed the XLA
    # latency-hiding scheduler flags (None = never touched)
    xla_async_collectives: Optional[bool] = None


class Engine:
    """Process-wide runtime state.  ``Engine.init()`` is idempotent.

    Reference parity: ``Engine.init`` (``DL/utils/Engine.scala:105-118``),
    ``Engine.nodeNumber()/coreNumber()`` → :meth:`node_number` /
    :meth:`core_number` report JAX process/device counts instead of Spark
    executors/cores.
    """

    _state = _EngineState()

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def init(cls, seed: int = 1) -> None:
        cls._state.initialized = True
        cls._state.seed = seed

    @classmethod
    def is_initialized(cls) -> bool:
        return cls._state.initialized

    @classmethod
    def reset(cls) -> None:
        cls._state = _EngineState()
        # the tuned-config cache is process state the Engine owns the
        # lifecycle of: a reset must also forget any loaded
        # tuned_configs.json so tests and multi-run processes cannot
        # leak a prior workload's tuned defaults (regression-gated in
        # tests/test_autotune.py)
        from bigdl_tpu.utils import tuned
        tuned.reset_cache()

    # -- topology ----------------------------------------------------------
    @classmethod
    def node_number(cls) -> int:
        """Number of hosts (reference: Spark executor count)."""
        return jax.process_count()

    @classmethod
    def core_number(cls) -> int:
        """Devices per host (reference: cores per executor)."""
        return jax.local_device_count()

    @classmethod
    def device_count(cls) -> int:
        return jax.device_count()

    # -- mesh --------------------------------------------------------------
    @classmethod
    def set_mesh(cls, mesh: Mesh) -> None:
        cls._state.mesh = mesh

    @classmethod
    def get_mesh(cls) -> Mesh:
        """The mesh distributed optimizers shard over.

        Defaults to a 1-D data-parallel mesh over all devices — the direct
        analog of the reference's one-replica-per-core data parallelism
        (``DistriOptimizer.scala:136-139``), minus the per-core replication
        (the batch is sharded over devices instead).
        """
        if cls._state.mesh is None:
            devs = np.array(jax.devices())
            cls._state.mesh = Mesh(devs, axis_names=("data",))
        return cls._state.mesh

    # -- config ------------------------------------------------------------
    @classmethod
    def seed(cls) -> int:
        return cls._state.seed

    @classmethod
    def set_workload(cls, tag: Optional[str]) -> None:
        """Tag the process-wide workload (``"ptb_lstm"``,
        ``"wide_deep"``, …) so tuned defaults from
        ``tuned_configs.json`` apply at call sites that don't carry
        their own tag — layer construction resolving ``kernel_impl``,
        for example.  ``None`` clears the tag.  Per-run tags
        (``Optimizer.set_workload``, ``InferenceService(workload=)``)
        take precedence over this one at their own call sites."""
        cls._state.workload = tag

    @classmethod
    def workload(cls) -> Optional[str]:
        return cls._state.workload

    @classmethod
    def _resolve(cls, knob: str, workload: Optional[str]):
        """Default chain below the Engine-level setters: configure()/
        env > tuned_configs.json (``workload@backend``) > dataclass
        default (utils/tuned.resolve_default)."""
        from bigdl_tpu.utils.tuned import resolve_default
        wl = workload if workload is not None else cls._state.workload
        value, _src = resolve_default(knob, workload=wl)
        return value

    @classmethod
    def steps_per_dispatch(cls, workload: Optional[str] = None) -> int:
        """How many train steps the driver fuses into one jit dispatch.
        Resolution: :meth:`set_steps_per_dispatch` (explicit,
        process-wide) > ``configure()``/``BIGDL_TPU_STEPS_PER_DISPATCH``
        > tuned_configs.json for ``workload`` (or the process-wide
        :meth:`workload` tag) > ``Config.steps_per_dispatch``."""
        if cls._state.steps_per_dispatch is not None:
            return max(1, int(cls._state.steps_per_dispatch))
        return max(1, int(cls._resolve("steps_per_dispatch", workload)))

    @classmethod
    def set_steps_per_dispatch(cls, k: int) -> None:
        if int(k) < 1:
            raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
        cls._state.steps_per_dispatch = int(k)

    @classmethod
    def kernel_impl(cls, workload: Optional[str] = None) -> str:
        """Process-wide custom-kernel choice (``auto|pallas|xla``) the
        pallas-backed layers resolve when built without an explicit
        ``impl=``; see ``Config.kernel_impl`` for the semantics and
        ``ops.resolve_kernel_impl`` for the auto rule.  Same default
        chain as :meth:`steps_per_dispatch`."""
        if cls._state.kernel_impl is not None:
            return cls._state.kernel_impl
        impl = cls._resolve("kernel_impl", workload)
        if impl not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"kernel_impl must be auto|pallas|xla, got {impl!r}")
        return impl

    @classmethod
    def set_kernel_impl(cls, impl: str) -> None:
        if impl not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"kernel_impl must be auto|pallas|xla, got {impl!r}")
        cls._state.kernel_impl = impl

    # -- serving -----------------------------------------------------------
    @classmethod
    def serving_defaults(cls, workload: Optional[str] = None) -> dict:
        """Process-wide defaults for :class:`bigdl_tpu.serving.
        InferenceService` knobs (config ``serving_*`` fields /
        ``BIGDL_TPU_SERVING_*`` env, each below a tuned_configs.json
        entry for ``workload``); per-service constructor args
        override.  ``row_buckets`` is the parsed-ready bucket spec
        string (``serving_row_buckets``; "" = power-of-two auto)."""
        return {
            "max_batch_size": cls._resolve("serving_max_batch_size",
                                           workload),
            "batch_timeout_ms": cls._resolve("serving_batch_timeout_ms",
                                             workload),
            "queue_capacity": cls._resolve("serving_queue_capacity",
                                           workload),
            "row_buckets": cls._resolve("serving_row_buckets", workload),
            # resilience: the per-request deadline a ReplicaSet stamps
            # on submissions (0 = none) — same resolution chain as the
            # other serving knobs so the autotuner can tune it per
            # workload
            "deadline_ms": cls._resolve("serving_deadline_ms", workload),
        }

    # -- XLA collective scheduling ----------------------------------------
    # The grad_sync design (parallel/grad_sync.py) leans on XLA's
    # latency-hiding scheduler to overlap per-bucket reduce-scatter /
    # all-gather with backward compute.  On TPU that scheduling is
    # governed by XLA flags that must be set BEFORE the backend
    # initializes; this is the one documented place to flip them.
    _ASYNC_COLLECTIVE_FLAGS = (
        "--xla_tpu_enable_latency_hiding_scheduler",
        "--xla_tpu_enable_async_collective_fusion",
    )

    @classmethod
    def set_xla_async_collectives(cls, enable: bool = True,
                                  force: bool = False) -> None:
        """Arm (or disarm) XLA's async-collective / latency-hiding
        scheduler flags via ``XLA_FLAGS``.  Call BEFORE the first jax
        computation — XLA reads the env once at backend init.

        The flags are TPU-build flags, and XLA ABORTS the whole process
        at backend init on flags its build doesn't know ("Unknown flags
        in XLA_FLAGS") — so before committing them to the environment
        this PROBES a throwaway subprocess with the new env; if that
        child cannot initialize jax, the intent is recorded
        (:meth:`xla_async_collectives`) but the env is left alone.
        Once this process's backend is live the probe is no longer
        trustworthy either (on a single-tenant TPU the child cannot
        acquire the chip the parent holds and would read as a bogus
        refusal), so a late call refuses with that diagnosis.
        ``force=True`` writes the flags with no probe in both cases
        (images known to accept them, or tests exercising the
        plumbing); after backend init they then apply to child
        processes only."""
        cls._state.xla_async_collectives = bool(enable)
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if f.split("=")[0] not in cls._ASYNC_COLLECTIVE_FLAGS]
        val = "true" if enable else "false"
        flags += [f"{f}={val}" for f in cls._ASYNC_COLLECTIVE_FLAGS]
        new_flags = " ".join(flags)
        import logging
        log = logging.getLogger("bigdl_tpu.engine")
        if os.environ.get("XLA_FLAGS", "") == new_flags:
            return  # already committed — nothing to probe or rewrite
        if not force:
            if cls._backend_live():
                log.warning(
                    "set_xla_async_collectives(%s) after backend init: "
                    "cannot probe flag acceptance safely (a TPU probe "
                    "child would fight this process for the chip) nor "
                    "retrofit the live backend — intent recorded, "
                    "XLA_FLAGS untouched.  Call before the first jax "
                    "computation, or force=True to write the flags for "
                    "child processes only", enable)
                return
            if not cls._xla_flags_survive(new_flags):
                log.warning(
                    "set_xla_async_collectives(%s): this jaxlib fatally "
                    "rejects the async-collective flags — intent "
                    "recorded, XLA_FLAGS untouched (force=True "
                    "overrides)", enable)
                return
        os.environ["XLA_FLAGS"] = new_flags
        if cls._backend_live():
            log.warning(
                "set_xla_async_collectives(%s) after backend init: flags "
                "apply to child processes only (XLA reads XLA_FLAGS once)",
                enable)

    @staticmethod
    def _backend_live() -> bool:
        """Whether this process's jax backend has already initialized
        (and therefore already consumed ``XLA_FLAGS``)."""
        try:
            from jax._src import xla_bridge
            return bool(getattr(xla_bridge, "_backends", None))
        except Exception:  # pragma: no cover - jax internals moved
            return False

    @staticmethod
    def _xla_flags_survive(xla_flags: str) -> bool:
        """Probe whether a jax process on this machine survives the
        given ``XLA_FLAGS`` (XLA's flag parser aborts the PROCESS on
        unknown flags, so this cannot be tested in-process)."""
        import subprocess
        import sys
        env = dict(os.environ)
        env["XLA_FLAGS"] = xla_flags
        # the probe inherits the DEFAULT backend choice: the known-flag
        # registry is per backend binary (libtpu knows --xla_tpu_*
        # flags, a CPU-only jaxlib does not), so a CPU-pinned child
        # would reject flags the real target accepts.  Tradeoff: on a
        # single-tenant TPU the child must be able to acquire the chip,
        # which is why this surface is documented as
        # call-before-the-first-jax-computation; a child that cannot
        # init reads as "refuse" (safe: flags just stay unset).
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                env=env, capture_output=True, timeout=300)
        except Exception:  # pragma: no cover - probe infrastructure
            return False
        return r.returncode == 0

    @classmethod
    def xla_async_collectives(cls) -> Optional[bool]:
        """Last value passed to :meth:`set_xla_async_collectives`
        (None = untouched defaults)."""
        return cls._state.xla_async_collectives
