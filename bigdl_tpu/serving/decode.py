"""Continuous-batching autoregressive decode (ROADMAP item 1, part b).

The batch-inference engine (``serving/service.py``) coalesces fixed-shape
requests into one dispatch — the right shape for encoder traffic, the
WRONG shape for autoregressive decode, where padding a request batch to
its slowest member holds a 4-token reply hostage to a 512-token one.
This module schedules at **iteration (step) granularity** instead — the
Orca/vLLM discipline:

- a **slotted KV cache** sized to a declared budget: k/v each
  ``(L, slots, H, max_seq_len, Dh)`` device arrays
  (``models/transformer.py`` decode carry); a sequence owns one slot
  from admission to EOS/max-tokens/deadline, then the slot is reclaimed
  the same step and the next queued sequence takes it;
- **prefill buckets** extending the PR-5 AOT ladder: prompts are padded
  to a sequence-length bucket (``parse_row_buckets`` — the grammar's
  ``pow2@<floor>`` form exists for exactly this) and every bucket's
  prefill + cache-splice executables are AOT-compiled at construction,
  so steady-state admission never traces;
- one **decode-step executable** over the full slot batch: every step
  advances ALL active sequences one token; new sequences are admitted
  into the running batch BETWEEN steps (never blocking on in-flight
  sequences finishing), which the accounting exposes as
  ``admit_step``/``finish_step`` on every :class:`DecodeResult`;
- **deadlines and per-tenant QoS ride the existing request path**: each
  queued sequence is a :class:`~bigdl_tpu.serving.batcher._Request`
  (deadline + RequestContext + future), admission under pressure ranks
  by the same ``priority_fn`` contract the batcher uses (frontend
  :class:`~bigdl_tpu.frontend.QosAdmission` plugs in unchanged), and an
  expired sequence — queued or mid-decode — settles
  :class:`DeadlineExceeded`;
- **token streaming**: ``submit(..., on_token=fn)`` delivers each token
  as generated (the frontend's chunked-ndjson generate route rides
  this).

Threading: ONE scheduler thread owns the device caches and all slot
bookkeeping (single-owner, no lock needed there); the cross-thread
surface (queue, lifecycle flags, active count) is guarded by ``_cond``'s
lock.  Metrics land on a :class:`~bigdl_tpu.serving.ServingMetrics`
(dispatch accounting reads as step occupancy: ``record_dispatch(active,
slots)`` per step, so ``mean_batch_occupancy`` is the continuous-batching
win the bench reports).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from bigdl_tpu.serving.batcher import (DeadlineExceeded, RequestSpecError,
                                       ServiceClosed, ServiceOverloaded,
                                       _Request, settle_future)
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.service import parse_row_buckets

logger = logging.getLogger("bigdl_tpu.serving")


class DecodeResult:
    """What a decode future resolves to.

    - ``tokens``: np.int32 array of generated tokens (includes the EOS
      token when ``finish_reason == "eos"``);
    - ``finish_reason``: ``"eos"`` | ``"length"`` (max-new-tokens or
      context cap);
    - ``admit_step`` / ``finish_step``: the scheduler's global step
      counter at admission / completion — the dispatch accounting that
      PROVES continuous batching (request B with ``A.admit_step <
      B.admit_step < A.finish_step`` joined A's running batch);
    - ``slot``: the KV-cache slot the sequence occupied (slot-reuse
      audits);
    - ``prompt_len`` / ``prefill_bucket``: request size and the AOT
      bucket its prefill padded into.
    """

    __slots__ = ("tokens", "finish_reason", "admit_step", "finish_step",
                 "slot", "prompt_len", "prefill_bucket")

    def __init__(self, tokens, finish_reason, admit_step, finish_step,
                 slot, prompt_len, prefill_bucket):
        self.tokens = tokens
        self.finish_reason = finish_reason
        self.admit_step = admit_step
        self.finish_step = finish_step
        self.slot = slot
        self.prompt_len = prompt_len
        self.prefill_bucket = prefill_bucket


class _Pending:
    """A queued decode request: the generic :class:`_Request` (future /
    deadline / ctx / t_enqueue — the existing request path) plus the
    decode-only fields that don't fit its __slots__."""

    __slots__ = ("req", "max_new", "on_token")

    def __init__(self, req: _Request, max_new: int, on_token):
        self.req = req
        self.max_new = max_new
        self.on_token = on_token


class _Sequence:
    """One active slot: scheduler-thread-owned bookkeeping."""

    __slots__ = ("pend", "prompt_len", "bucket", "generated",
                 "admit_step", "slot")

    def __init__(self, pend: _Pending, prompt_len: int, bucket: int,
                 admit_step: int, slot: int):
        self.pend = pend
        self.prompt_len = prompt_len
        self.bucket = bucket
        self.generated: List[int] = []
        self.admit_step = admit_step
        self.slot = slot


class DecodeService:
    """Continuous-batching decode engine for one ``transformer_lm``.

    Parameters:

    - ``slots``: concurrent-sequence capacity (the decode batch width).
    - ``max_seq_len``: per-sequence context cap (prompt + generated);
      clamped to the model's positional-embedding table.
    - ``kv_budget_mb``: declared KV-cache budget.  The cache is sized
      up front (two ``(L, slots, H, max_seq_len, Dh)`` f32 arrays); if
      that exceeds the budget, ``slots`` is CUT to what fits (raising
      if not even one slot fits) — the budget is a hard cap, not a
      hint.
    - ``prefill_buckets``: sequence-length bucket spec
      (:func:`~bigdl_tpu.serving.service.parse_row_buckets` grammar
      over ``max_prompt_len``; default ``"pow2@8"``).
    - ``eos_id``: token id that finishes a sequence (None = length-only
      stopping); ``default_max_new_tokens`` caps generation when the
      caller doesn't.
    - ``deadline_ms``: default per-request deadline (0/None = none).
    - ``mesh``: optional :class:`~jax.sharding.Mesh` — params are
      placed with the model's declared ``param_specs`` shardings
      (the ``ShardedReplicaSet`` discipline), making this a
      sharded-decode backend.
    - ``priority_fn``: the batcher's QoS contract — maps a queued
      ``_Request`` to an int rank (lower admits first), engaged only
      under pressure (more queued than free slots).

    Greedy (argmax) decoding — deterministic, so serving output equals
    the full-context reference run token-for-token (the acceptance
    gate).
    """

    # duck-type marker the frontend's generate route checks — a backend
    # without it answers 400 (predict backends don't decode)
    is_decode_backend = True

    def __init__(self, model, params=None, state=None, *,
                 slots: int = 4, max_seq_len: int = 256,
                 max_prompt_len: Optional[int] = None,
                 default_max_new_tokens: int = 32,
                 eos_id: Optional[int] = None,
                 prefill_buckets: Optional[str] = None,
                 kv_budget_mb: Optional[float] = None,
                 queue_capacity: int = 64,
                 deadline_ms: Optional[float] = None,
                 name: str = "decode", mesh=None,
                 registry=None, priority_fn=None, start: bool = True):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.models.transformer import (kv_cache_spec, lm_layout,
                                                  transformer_lm_decode_step,
                                                  transformer_lm_prefill)
        self.name = name
        self._model = model
        _, pos_mod, blocks, _, _, mha = lm_layout(model)  # validates layout
        if params is None:
            model._ensure_init()
            params, state = model._params, model._state
        self.max_seq_len = int(min(max_seq_len, pos_mod.max_len))
        if self.max_seq_len < 2:
            raise ValueError(f"max_seq_len must be >= 2: {self.max_seq_len}")
        self.max_prompt_len = int(max_prompt_len
                                  if max_prompt_len is not None
                                  else self.max_seq_len - 1)
        if not 1 <= self.max_prompt_len < self.max_seq_len:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} must leave room "
                f"for >= 1 generated token under max_seq_len "
                f"{self.max_seq_len}")
        self.eos_id = eos_id
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.queue_capacity = int(queue_capacity)
        self.deadline_s = (float(deadline_ms) / 1e3
                           if deadline_ms and deadline_ms > 0 else None)
        self.buckets = parse_row_buckets(prefill_buckets or "pow2@8",
                                         self.max_prompt_len)

        # KV budget: price the cache BEFORE allocating; the declared
        # budget wins over the requested slot count
        slots = int(slots)
        if slots < 1:
            raise ValueError(f"slots must be >= 1: {slots}")
        shape, dtype = kv_cache_spec(model, 1, self.max_seq_len)
        per_slot = 2 * int(np.prod(shape)) * jnp.dtype(dtype).itemsize
        if kv_budget_mb is not None:
            afford = int(kv_budget_mb * (1 << 20)) // per_slot
            if afford < 1:
                raise ValueError(
                    f"kv_budget_mb={kv_budget_mb} cannot hold one slot "
                    f"({per_slot / (1 << 20):.2f} MB/slot at "
                    f"max_seq_len={self.max_seq_len})")
            slots = min(slots, afford)
        self.slots = slots
        self.kv_bytes = per_slot * slots

        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from bigdl_tpu.parallel.tensor_parallel import build_param_specs
            specs = build_param_specs(model, params)
            params = jax.tree_util.tree_map(
                lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
                params, specs)
        self._params = params
        self._mesh = mesh

        self.metrics = ServingMetrics(registry)
        reg = self.metrics.registry
        self._c_steps = reg.counter("decode/steps")
        self._c_tokens = reg.counter("decode/tokens_generated")
        self._c_admissions = reg.counter("decode/admissions")
        self._c_reclaims = reg.counter("decode/slots_reclaimed")
        self._c_active_steps = reg.counter("decode/active_slot_steps")

        self._priority_fn = priority_fn
        self._priority_aging_s = 0.5  # same starvation bound as batcher

        # ---- cross-thread state --------------------------------------
        self._cond = threading.Condition()
        self._queue: deque[_Pending] = deque()  # guarded-by: _cond
        self._n_active = 0       # guarded-by: _cond
        self._stopping = False   # guarded-by: _cond
        self._drain = True       # guarded-by: _cond
        self._steps_done = 0     # guarded-by: _cond
        # step-seconds EWMA; written by the scheduler only, read racily
        # for overload retry hints (a stale hint is still a hint)
        self._step_ewma: Optional[float] = None
        self._thread: Optional[threading.Thread] = None  # guarded-by: _cond

        # ---- scheduler-thread-owned state (single owner: the decode
        # loop; constructed here before the thread exists) -------------
        self._seqs: List[Optional[_Sequence]] = [None] * slots
        self._lengths = np.zeros((slots,), np.int32)  # cached positions
        self._last_tok = np.zeros((slots,), np.int32)
        full, fdtype = kv_cache_spec(model, slots, self.max_seq_len)
        self._k = jnp.zeros(full, fdtype)
        self._v = jnp.zeros(full, fdtype)

        # ---- AOT executables -----------------------------------------
        # the PR-5 trace-count discipline: tracing happens ONLY during
        # this warmup; a steady-state retrace is a bug tests can gate on
        self._trace_count = 0

        def _prefill_fn(p, tokens):
            return transformer_lm_prefill(model, p, tokens)

        def _splice_fn(k, v, kp, vp, slot):
            # write a (L, 1, H, Tb, Dh) prefill cache into the slot
            k2 = jax.lax.dynamic_update_slice(k, kp, (0, slot, 0, 0, 0))
            v2 = jax.lax.dynamic_update_slice(v, vp, (0, slot, 0, 0, 0))
            return k2, v2

        def _step_fn(p, tokens, lengths, k, v):
            return transformer_lm_decode_step(model, p, tokens, lengths,
                                              k, v)

        def _aot(jitted, *avals):
            # compile counting lives HERE, in host code, not as a side
            # effect inside the traced functions: every executable is
            # `.lower().compile()`d exactly once per call of this
            # helper, and a Compiled object can never retrace — so
            # compile_count is frozen after the ctor by construction
            self._trace_count += 1
            return jitted.lower(*avals).compile()

        sds = jax.ShapeDtypeStruct
        i32 = jnp.int32
        L, _, H, _, Dh = full
        if mesh is not None:
            # every KV seam carries ONE declared NamedSharding — the
            # slot cache, the per-bucket prefill outputs, and each
            # executable's in/out avals (heads over the model axis when
            # it divides them; logits and token vectors replicated).
            # Left to GSPMD, prefill picks a model-sharded output
            # layout while splice compiles for a single device, and the
            # AOT call is rejected at dispatch with a sharding
            # mismatch.
            m_sz = mesh.shape.get("model", 1)
            kv_axis = "model" if (m_sz > 1 and H % m_sz == 0) else None
            rep_sh = NamedSharding(mesh, P())
            kv_sh = NamedSharding(mesh,
                                  P(None, None, kv_axis, None, None))
            self._k = jax.device_put(self._k, kv_sh)
            self._v = jax.device_put(self._v, kv_sh)
            lkv_out = {"out_shardings": (rep_sh, kv_sh, kv_sh)}
            kv_out = {"out_shardings": (kv_sh, kv_sh)}
        else:
            rep_sh = kv_sh = None
            lkv_out = kv_out = {}
        kspec = sds(full, fdtype, sharding=kv_sh)
        self._step_exec = _aot(
            jax.jit(_step_fn, **lkv_out), self._params,
            sds((slots,), i32, sharding=rep_sh),
            sds((slots,), i32, sharding=rep_sh), kspec, kspec)
        jit_prefill = jax.jit(_prefill_fn, **lkv_out)
        jit_splice = jax.jit(_splice_fn, **kv_out)
        self._prefill_exec = {}
        self._splice_exec = {}
        for tb in self.buckets:
            pseq = sds((L, 1, H, tb, Dh), fdtype, sharding=kv_sh)
            self._prefill_exec[tb] = _aot(
                jit_prefill, self._params,
                sds((1, tb), i32, sharding=rep_sh))
            self._splice_exec[tb] = _aot(
                jit_splice, kspec, kspec, pseq, pseq,
                sds((), i32, sharding=rep_sh))

        if start:
            self.start()

    # ------------------------------------------------------------ control
    def start(self) -> "DecodeService":
        with self._cond:
            if self._thread is None:
                t = threading.Thread(target=self._run,
                                     name=f"decode-sched/{self.name}",
                                     daemon=True)
                self._thread = t
                t.start()
        return self

    @property
    def alive(self) -> bool:
        with self._cond:
            t = self._thread
        return t is not None and t.is_alive()

    @property
    def max_batch_size(self) -> int:
        """Slot capacity — the backend-contract name the frontend's
        request validators expect."""
        return self.slots

    @property
    def row_spec(self):
        """Backend-contract compatibility (``HotCutover`` / registry
        introspection): decode requests are token prompts, not fixed
        row shapes — there is no per-row spec to advertise."""
        return None

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def steps_done(self) -> int:
        with self._cond:
            return self._steps_done

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0):
        """Refuse new work; with ``drain`` finish every queued + active
        sequence first, else cancel them (``ServiceClosed``)."""
        with self._cond:
            self._stopping = True
            self._drain = bool(drain)
            t = self._thread
            self._cond.notify_all()
        if t is not None:
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------- submit
    def submit(self, prompt, *, max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None, ctx=None,
               on_token: Optional[Callable[[int, int], None]] = None):
        """Enqueue one prompt (1-D int array/list).  Returns a Future
        resolving to a :class:`DecodeResult`.  ``on_token(index,
        token_id)`` fires from the scheduler thread as each token is
        generated — it must not block (the streaming route hands tokens
        to its own writer).  ``deadline`` is absolute monotonic seconds
        (the frontend's ``X-Deadline-Ms`` path); default from
        ``deadline_ms``."""
        x = np.asarray(prompt)
        if x.ndim != 1 or x.size < 1 or not np.issubdtype(x.dtype,
                                                          np.integer):
            raise RequestSpecError(
                f"prompt must be a non-empty 1-D int array, got "
                f"shape {x.shape} dtype {x.dtype}")
        if x.size > self.max_prompt_len:
            raise RequestSpecError(
                f"prompt length {x.size} > max_prompt_len "
                f"{self.max_prompt_len}")
        max_new = (int(max_new_tokens) if max_new_tokens is not None
                   else self.default_max_new_tokens)
        if max_new < 1:
            raise RequestSpecError(f"max_new_tokens must be >= 1: "
                                   f"{max_new}")
        max_new = min(max_new, self.max_seq_len - int(x.size))
        if deadline is None and self.deadline_s is not None:
            deadline = time.monotonic() + self.deadline_s
        req = _Request(x.astype(np.int32), 1, deadline=deadline, ctx=ctx)
        pend = _Pending(req, max_new, on_token)
        with self._cond:
            if self._stopping:
                raise ServiceClosed(f"decode service {self.name!r} is "
                                    f"stopping")
            if len(self._queue) >= self.queue_capacity:
                self.metrics.record_reject(1)
                raise ServiceOverloaded(
                    len(self._queue), self.queue_capacity, self.name,
                    retry_after_ms=self._retry_hint_locked())
            self._queue.append(pend)
            self._cond.notify_all()
        self.metrics.record_submit(1)
        return req.future

    def generate(self, prompt, **kw) -> DecodeResult:
        """Blocking sugar over :meth:`submit`."""
        return self.submit(prompt, **kw).result()

    def _retry_hint_locked(self) -> Optional[float]:  # guarded-by: _cond
        """Queue-drain estimate: steps to free a slot times step time.
        Coarse by design — a shed caller needs a magnitude, not a
        promise."""
        ew = self._step_ewma
        if ew is None:
            return None
        waves = (len(self._queue) + self.slots) / max(1, self.slots)
        return ew * 1e3 * waves * max(1, self.default_max_new_tokens // 4)

    # ---------------------------------------------------------- scheduler
    def _rank_locked(self, pend: _Pending, now: float) -> int:
        """The batcher's effective-rank rule verbatim: declared rank
        minus one class per aging period waited; a broken priority_fn
        ranks most-urgent instead of killing the scheduler."""
        try:
            rank = int(self._priority_fn(pend.req))
        except Exception:
            return 0
        return rank - int((now - pend.req.t_enqueue)
                          / self._priority_aging_s)

    # guarded-by: _cond
    def _pick_admissions_locked(self, free: int) -> List[_Pending]:
        """Pop up to ``free`` queued sequences.  FIFO under light load;
        with a ``priority_fn`` and more queued than admissible, best
        (effective rank, arrival) wins — the batcher's pressure rule at
        slot granularity."""
        if free <= 0 or not self._queue:
            return []
        picked: List[_Pending] = []
        pressure = (self._priority_fn is not None
                    and len(self._queue) > free)
        now = time.monotonic()
        for _ in range(min(free, len(self._queue))):
            if pressure:
                best = min(range(len(self._queue)),
                           key=lambda i: (self._rank_locked(
                               self._queue[i], now),
                               self._queue[i].req.t_enqueue))
                picked.append(self._queue[best])
                del self._queue[best]
            else:
                picked.append(self._queue.popleft())
        return picked

    def _emit(self, seq: _Sequence, index: int, token: int) -> None:
        cb = seq.pend.on_token
        if cb is None:
            return
        try:
            cb(index, token)
        except Exception:
            logger.exception("decode on_token callback failed "
                             "(model=%s)", self.name)

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _admit(self, pend: _Pending, slot: int) -> None:
        """Prefill one sequence into ``slot`` (scheduler thread)."""
        import jax.numpy as jnp
        req = pend.req
        now = time.monotonic()
        if req.deadline is not None and now >= req.deadline:
            if settle_future(req.future, exc=DeadlineExceeded(
                    f"deadline expired before admission "
                    f"(model={self.name})")):
                self.metrics.record_failure(1)
            return
        prompt = req.x
        n = int(prompt.shape[0])
        tb = self._bucket_for(n)
        padded = np.zeros((1, tb), np.int32)
        padded[0, :n] = prompt
        lp, kp, vp = self._prefill_exec[tb](self._params,
                                            jnp.asarray(padded))
        self._k, self._v = self._splice_exec[tb](
            self._k, self._v, kp, vp, np.int32(slot))
        self.metrics.record_dispatch(1, 1)  # prefill dispatch
        first = int(np.asarray(lp)[0, n - 1].argmax())
        with self._cond:
            admit_step = self._steps_done
            self._n_active += 1
        seq = _Sequence(pend, n, tb, admit_step, slot)
        self._seqs[slot] = seq
        self._lengths[slot] = n
        self._last_tok[slot] = first
        self._c_admissions.inc()
        seq.generated.append(first)
        self._c_tokens.inc()
        self._emit(seq, 0, first)
        # a 1-token request (or instant EOS) finishes without ever
        # joining the step batch
        self._maybe_finish(seq, first)

    def _finish(self, seq: _Sequence, reason: str) -> None:
        with self._cond:
            finish_step = self._steps_done
            self._n_active -= 1
            self._cond.notify_all()
        self._seqs[seq.slot] = None
        self._lengths[seq.slot] = 0
        self._last_tok[seq.slot] = 0
        self._c_reclaims.inc()
        res = DecodeResult(np.asarray(seq.generated, np.int32), reason,
                           seq.admit_step, finish_step, seq.slot,
                           seq.prompt_len, seq.bucket)
        if settle_future(seq.pend.req.future, result=res):
            self.metrics.record_done(
                1, time.monotonic() - seq.pend.req.t_enqueue,
                bucket=seq.bucket)

    def _fail(self, seq: _Sequence, exc: BaseException) -> None:
        with self._cond:
            self._n_active -= 1
            self._cond.notify_all()
        self._seqs[seq.slot] = None
        self._lengths[seq.slot] = 0
        self._last_tok[seq.slot] = 0
        self._c_reclaims.inc()
        if settle_future(seq.pend.req.future, exc=exc):
            self.metrics.record_failure(1)

    def _maybe_finish(self, seq: _Sequence, token: int) -> bool:
        if self.eos_id is not None and token == self.eos_id:
            self._finish(seq, "eos")
            return True
        if len(seq.generated) >= seq.pend.max_new:
            self._finish(seq, "length")
            return True
        if seq.prompt_len + len(seq.generated) >= self.max_seq_len:
            self._finish(seq, "length")
            return True
        return False

    def _step(self) -> None:
        """One decode iteration over the slot batch (scheduler thread):
        every active sequence's last token is written to its cache and
        its next token decoded — ONE executable run regardless of how
        many sequences are active (the inactive lanes compute discarded
        garbage; occupancy is the metric that prices this)."""
        import jax.numpy as jnp
        t0 = time.monotonic()
        active = [s for s in self._seqs if s is not None]
        lp, self._k, self._v = self._step_exec(
            self._params, jnp.asarray(self._last_tok),
            jnp.asarray(self._lengths), self._k, self._v)
        lp_host = np.asarray(lp)  # device sync point
        dt = time.monotonic() - t0
        self._step_ewma = (dt if self._step_ewma is None
                           else 0.8 * self._step_ewma + 0.2 * dt)
        with self._cond:
            self._steps_done += 1
        self._c_steps.inc()
        self._c_active_steps.inc(len(active))
        self.metrics.record_dispatch(len(active), self.slots)
        now = time.monotonic()
        for seq in active:
            # cache grew by one position (the step wrote last_tok's K/V)
            self._lengths[seq.slot] += 1
            if (seq.pend.req.deadline is not None
                    and now >= seq.pend.req.deadline):
                self._fail(seq, DeadlineExceeded(
                    f"deadline expired mid-decode after "
                    f"{len(seq.generated)} tokens (model={self.name})"))
                continue
            tok = int(lp_host[seq.slot].argmax())
            self._last_tok[seq.slot] = tok
            seq.generated.append(tok)
            self._c_tokens.inc()
            self._emit(seq, len(seq.generated) - 1, tok)
            self._maybe_finish(seq, tok)

    def _cancel_backlog_locked(self) -> List[_Pending]:  # guarded-by: _cond
        out = list(self._queue)
        self._queue.clear()
        return out

    def _run(self) -> None:
        """The decode loop.  Each pass: admit queued sequences into free
        slots (prefill off the lock), then run one step if anything is
        active.  Blocks on the condition when idle.  An unexpected
        exception anywhere in the loop fails every in-flight future
        with it instead of dying silently — a crashed scheduler with
        live futures would park every ``generate()`` caller forever."""
        cancelled: List[_Pending] = []
        crash: Optional[BaseException] = None
        try:
            while True:
                with self._cond:
                    while (not self._stopping and not self._queue
                           and self._n_active == 0):
                        self._cond.wait()
                    if self._stopping and (
                            not self._drain
                            or (not self._queue and self._n_active == 0)):
                        cancelled = self._cancel_backlog_locked()
                        break
                    free = self.slots - self._n_active
                    to_admit = self._pick_admissions_locked(free)
                for slot in range(self.slots):
                    if not to_admit:
                        break
                    if self._seqs[slot] is None:
                        self._admit(to_admit.pop(0), slot)
                if any(s is not None for s in self._seqs):
                    self._step()
        except Exception as e:
            logger.exception("decode scheduler crashed (model=%s)",
                             self.name)
            crash = e
            with self._cond:
                self._stopping = True  # submit() refuses from here on
                cancelled = self._cancel_backlog_locked()
                self._cond.notify_all()
        # non-drain stop (or crash): settle queued work and active
        # sequences — the crash exception propagates to every caller
        exc = crash if crash is not None else ServiceClosed(
            f"decode service {self.name!r} stopped")
        for pend in cancelled:
            if settle_future(pend.req.future, exc=exc):
                if crash is None:
                    self.metrics.record_cancel(1)
                else:
                    self.metrics.record_failure(1)
        for seq in list(self._seqs):
            if seq is not None:
                self._fail(seq, exc)

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        """The ``service.stats()`` schema plus a ``decode`` section:
        step/token/admission accounting and step-level occupancy
        (active-slot-steps over total slot-steps — the continuous-
        batching utilization figure)."""
        with self._cond:
            qd = len(self._queue)
            steps = self._steps_done
            active = self._n_active
        snap = self.metrics.snapshot(queue_depth=qd,
                                     compile_count=self._trace_count)
        ew = self._step_ewma
        snap["decode"] = {
            "slots": self.slots,
            "active": active,
            "steps": steps,
            "tokens_generated": self._c_tokens.value,
            "admissions": self._c_admissions.value,
            "slots_reclaimed": self._c_reclaims.value,
            "step_occupancy": (
                round(self._c_active_steps.value / (steps * self.slots), 4)
                if steps else None),
            "step_ms_ewma": round(ew * 1e3, 3) if ew is not None else None,
            "prefill_buckets": list(self.buckets),
            "max_seq_len": self.max_seq_len,
            "kv_bytes": self.kv_bytes,
        }
        return snap
