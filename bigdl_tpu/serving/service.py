"""InferenceService — dynamic batching over AOT-compiled bucket executables.

The TPU-native serving contract (README "serving"):

- **One compiled forward per row-bucket, compiled at deploy time.**
  Steady-state traffic must never trace or compile: coalesced batches
  are padded up to the nearest power-of-two row bucket and every bucket
  executable is built up-front with ``jax.jit(...).lower(...).compile()``
  — the same recompile-hazard discipline graftlint GL106 enforces for
  training loops, applied to the serving path (catalog note in
  ``tools/graftlint/README.md``).
- **Zero padding, sliced off.**  Padded rows are zeros, never copies of
  real rows: the invariant inference relies on is that the forward is
  row-independent in eval mode (BatchNorm uses running stats, dropout is
  off), so pad values cannot leak into real rows and are simply sliced
  away.  Zeros keep the H2D transfer compressible and make the invariant
  auditable — a pad row that *did* influence output would change results
  between bucket sizes, which the serving tests gate bitwise.
- **Futures in, backpressure out.**  ``submit`` enqueues and returns a
  ``concurrent.futures.Future``; a full bounded queue raises
  ``ServiceOverloaded`` (queue depth in the message) instead of
  buffering into timeout territory.  ``predict`` is the blocking sugar
  (and chunks oversized inputs across several requests).
"""

from __future__ import annotations

import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.serving.batcher import (
    DeadlineExceeded, RequestBatcher, RequestSpecError, ServiceClosed,
    ServiceOverloaded, _Request, settle_future,
)
from bigdl_tpu.serving.metrics import ServingMetrics

_tree = jax.tree_util


def row_buckets(max_batch_size: int, floor: int = 1) -> Tuple[int, ...]:
    """Power-of-two row buckets up to ``max_batch_size`` (inclusive —
    a non-power-of-two max becomes the top bucket so a full coalesced
    batch never spills into two dispatches).  ``floor`` starts the
    ladder higher than 1 — sequence-length ladders (decode prefill)
    have no use for 1/2/4-token executables."""
    bs = []
    b = max(1, int(floor))
    while b < max_batch_size:
        bs.append(b)
        b *= 2
    bs.append(max_batch_size)
    return tuple(bs)


def parse_row_buckets(spec: str, max_batch_size: int) -> Tuple[int, ...]:
    """Parse a ``Config.serving_row_buckets`` bucket-set spec:

    - ``""`` / ``"pow2"`` — :func:`row_buckets` power-of-two auto (the
      default);
    - ``"top"`` — one bucket at ``max_batch_size`` (maximum executable
      sharing, maximum padding — the autotuner's coarse-granularity
      grid point);
    - ``"pow2@16"`` — power-of-two ladder FLOORED at 16: the
      sequence-length form of the grammar (decode prefill buckets in
      ``serving/decode.py``, where ``max_batch_size`` is the max
      prompt length and sub-floor executables are wasted compiles);
    - ``"8,16,32"`` — explicit ascending positive ints whose top must
      cover ``max_batch_size`` (a full coalesced batch always has a
      bucket to pad into).
    """
    s = (spec or "").strip()
    if s in ("", "pow2"):
        return row_buckets(max_batch_size)
    if s == "top":
        return (max_batch_size,)
    if s.startswith("pow2@"):
        try:
            floor = int(s[5:])
        except ValueError:
            raise ValueError(
                f"bucket spec {spec!r}: pow2@<floor> needs an int "
                f"floor") from None
        if floor < 1:
            raise ValueError(f"bucket floor must be >= 1: {floor}")
        return row_buckets(max_batch_size, floor)
    try:
        buckets = tuple(int(tok) for tok in s.split(","))
    except ValueError:
        raise ValueError(
            f"row-bucket spec {spec!r} must be '', 'pow2', 'top' or a "
            f"comma-separated int list") from None
    if (not buckets or any(b < 1 for b in buckets)
            or list(buckets) != sorted(set(buckets))):
        raise ValueError(
            f"row buckets {buckets} must be ascending unique positive "
            f"ints")
    if buckets[-1] < max_batch_size:
        raise ValueError(
            f"top row bucket {buckets[-1]} < max_batch_size "
            f"{max_batch_size} — a full coalesced batch would have no "
            f"bucket to pad into")
    return buckets


def leading_rows(x) -> int:
    # RequestSpecError (a ValueError): the REQUEST is malformed — the
    # wire frontend maps it to 400 instead of a server-fault 500
    leaves = _tree.tree_leaves(x)
    if not leaves:
        raise RequestSpecError("empty input pytree")
    n = leaves[0].shape[0] if leaves[0].ndim else None
    for leaf in leaves:
        if leaf.ndim == 0 or leaf.shape[0] != n:
            raise RequestSpecError(
                "all input leaves must share one leading batch dim; got "
                f"shapes {[leaf.shape for leaf in leaves]}")
    return n


def pad_rows(x, target: int):
    """Zero-pad every leaf's leading dim up to ``target`` rows (see the
    module docstring for why zeros and not row copies)."""

    def pad(leaf):
        n = leaf.shape[0]
        if n == target:
            return leaf
        widths = [(0, target - n)] + [(0, 0)] * (leaf.ndim - 1)
        return np.pad(leaf, widths)

    return _tree.tree_map(pad, x)


def _detect_weights_dtype(model, params) -> str:
    """Classify the served model's weight storage: ``"int8"`` when any
    quantized twin (``nn.quantized``) is in the module tree, else
    ``"bf16"``/``"f32"`` from the param leaves.  Host-side, walked once
    at service construction — the ``weights_dtype`` tag the int8
    serving rollout gates on (stats()/``/metrics``)."""
    from bigdl_tpu.nn.module import Container
    from bigdl_tpu.nn.quantized import (QuantizedLinear,
                                        QuantizedSpatialConvolution,
                                        _QuantizedCellBase)
    from bigdl_tpu.nn.recurrent import BiRecurrent, Recurrent
    stack = [model]
    while stack:
        m = stack.pop()
        if isinstance(m, (QuantizedLinear, QuantizedSpatialConvolution,
                          _QuantizedCellBase)):
            return "int8"
        if isinstance(m, Container):
            stack.extend(m.modules)
        elif isinstance(m, Recurrent):
            stack.append(m.cell)
        elif isinstance(m, BiRecurrent):
            stack.extend((m.fwd, m.bwd))
    for leaf in jax.tree_util.tree_leaves(params):
        if getattr(leaf, "dtype", None) == jnp.bfloat16:
            return "bf16"
    return "f32"


class InferenceService:
    """Always-on inference endpoint for one model.

    Parameters
    ----------
    model, params, state:
        Any :class:`~bigdl_tpu.nn.module.Module` (including the
        ``nn.quantized`` int8 twins and interop-loaded models); params
        default to the model's own initialized weights.
    input_spec:
        Pytree of per-ROW ``jax.ShapeDtypeStruct`` (no batch dim) — or
        ``(shape, dtype)`` tuples / np arrays — describing one request
        row.  When given, all bucket executables are AOT-compiled at
        construction (deploy-time warmup); when ``None``, the spec is
        captured from the first request and warmup happens then (the
        back-compat ``PredictionService`` path).
    max_batch_size / batch_timeout_ms / queue_capacity / buckets:
        Coalescing and backpressure knobs; ``None`` resolves from
        ``Engine.serving_defaults(workload)`` (config ``serving_*``
        fields / ``BIGDL_TPU_SERVING_*`` env, each sitting above a
        ``tuned_configs.json`` entry for ``workload`` and the
        dataclass default — the documented resolution chain).
        ``buckets`` is either an explicit ascending int tuple or a
        :func:`parse_row_buckets` spec string ("pow2" / "top" /
        "8,16,32").
    workload:
        Tuned-config key this service's knob defaults resolve under
        (e.g. the tag ``tools/autotune.py --workload`` tuned).  None =
        config/env/dataclass defaults only.
    start:
        ``start=False`` builds the service with the batcher parked —
        requests queue (bounded) until :meth:`start`.  Used by tests to
        stage deterministic coalescing, and by deploys that want warmup
        strictly before traffic.
    fault_injector:
        Optional :class:`~bigdl_tpu.resilience.faults.FaultInjector`
        consulted once per coalesced dispatch (keyed by this service's
        own dispatch counter) — the chaos hook the resilience tests and
        ``bench.py --resilience`` drive.  ``None`` (the default) is the
        provably-inert state: the dispatch path never touches it.
    priority_fn:
        Optional QoS preemption hook handed to the
        :class:`~bigdl_tpu.serving.batcher.RequestBatcher`: maps an
        enqueued request (it carries ``.ctx`` with the tenant tag) to
        an int rank, lower dispatching first — engaged only when the
        queue holds more rows than one dispatch can carry.  ``None``
        (the default) keeps the batcher byte-identical FIFO.  The
        frontend's :class:`~bigdl_tpu.frontend.QosAdmission` supplies
        its ``priority_fn`` here.
    tracer / request_tracing:
        Request-scoped observability (telemetry round 2).  ``tracer``
        is an optional :class:`~bigdl_tpu.telemetry.Tracer` — submit
        and dispatch land as spans, with Chrome flow events fanning
        the N coalesced request spans into their one dispatch span.
        ``request_tracing`` (None = ``Config.request_tracing``) mints a
        :class:`~bigdl_tpu.telemetry.RequestContext` per submit when no
        explicit context is passed; off (the default), no context is
        ever allocated and the request path is byte-identical.
    """

    def __init__(self, model, params=None, state=None, *,
                 input_spec=None, max_batch_size: Optional[int] = None,
                 batch_timeout_ms: Optional[float] = None,
                 queue_capacity: Optional[int] = None,
                 buckets=None, workload: Optional[str] = None,
                 name: str = "model", start: bool = True,
                 fault_injector=None, tracer=None,
                 request_tracing: Optional[bool] = None,
                 priority_fn=None):
        from bigdl_tpu.engine import Engine
        self.workload = workload
        defaults = Engine.serving_defaults(workload)
        self.model = model
        if params is None:
            model._ensure_init()
            params, state = model._params, model._state
        self.params = params
        self.state = state if state is not None else {}
        self.name = name
        # `is not None` throughout: an explicit 0 must reach the
        # batcher's >= 1 validation, not silently become the default
        self.max_batch_size = int(
            max_batch_size if max_batch_size is not None
            else defaults["max_batch_size"])
        self.batch_timeout_ms = float(
            batch_timeout_ms if batch_timeout_ms is not None
            else defaults["batch_timeout_ms"])
        self.queue_capacity = int(
            queue_capacity if queue_capacity is not None
            else defaults["queue_capacity"])
        if buckets is None:
            buckets = defaults.get("row_buckets", "")
        if isinstance(buckets, str):
            self.buckets = parse_row_buckets(buckets, self.max_batch_size)
        else:
            # explicit tuple takes the same validation path: round-trip
            # through the spec grammar so ad-hoc bucket sets obey the
            # ascending/top-covers-max invariants too
            self.buckets = parse_row_buckets(
                ",".join(str(int(b)) for b in buckets),
                self.max_batch_size)

        # the ONE jit for this model; bucket executables are AOT builds
        # of it.  _trace_count counts Python traces — after warmup it
        # must never move (gated in tests/test_serving.py).
        self._trace_count = 0

        def fwd(params, state, x):
            # trace-time side effect BY DESIGN: runs once per Python
            # trace (= per compile), never in the compiled program —
            # it's the compile counter the zero-recompile gate reads
            self._trace_count += 1  # graftlint: disable=GL103
            out, _ = model.apply(params, state, x, training=False)
            return out

        self._jit = jax.jit(fwd)
        self._warm_lock = threading.Lock()
        # warmup state: written only under _warm_lock (warmup is the
        # one writer); hot-path reads are lock-free and gated on the
        # _warmed flag flipping LAST — readers never see a
        # partially-populated bucket dict
        self._compiled: Dict[int, Any] = {}  # write-guarded-by: _warm_lock
        self._warmed = False                 # write-guarded-by: _warm_lock
        self._row_spec = None                # write-guarded-by: _warm_lock
        self._out_spec = None                # write-guarded-by: _warm_lock
        # write-guarded-by: _warm_lock
        self._out_row_shape: Optional[Tuple[int, ...]] = None
        # serializes batcher replacement vs shutdown: revive() (on a
        # supervisor/failover thread) swaps in a new batcher and
        # start()s it; a concurrent stop() must never observe the new
        # thread object between creation and start() completing — a
        # join() there raises "cannot join thread before it is
        # started" (race surfaced by the obs-plane failover tests)
        self._lifecycle_lock = threading.Lock()
        self._stopped = False  # write-guarded-by: _lifecycle_lock
        self.metrics = ServingMetrics()
        # weights-dtype tag (int8 speed-path PR): detected once here,
        # surfaced in stats() and the pre-created /metrics gauge so the
        # registry's per-version rollout gates can see WHAT dtype each
        # deployed version serves (absent in old snapshots = "f32")
        self.weights_dtype = _detect_weights_dtype(model, self.params)
        self.metrics.set_weights_dtype(self.weights_dtype)
        # fault injection (resilience layer): the injector is consulted
        # per dispatch; _fault_replica is stamped by ReplicaSet so
        # target= clauses can aim at one replica of a set
        self._faults = fault_injector
        self._fault_replica: Optional[int] = None
        self._dispatch_index = 0
        self._priority_fn = priority_fn
        # request-scoped observability (telemetry round 2): resolved
        # ONCE here — the submit/dispatch hot paths only test the
        # resulting attributes, never read config
        self.tracer = tracer
        if request_tracing is None:
            from bigdl_tpu.utils.config import get_config
            request_tracing = get_config().request_tracing
        self._request_tracing = bool(request_tracing)
        # admin plane: config-driven start (admin_port=0 → None, no
        # thread) and source registration.  The scrape name is minted
        # unique (two same-named services must not evict each other,
        # and THIS service's stop() must only deregister a name it
        # owns); a retired name is released for the next deploy.
        from bigdl_tpu.telemetry import admin as _admin
        self._admin_name: Optional[str] = None
        _srv = _admin.maybe_start()
        if _srv is not None:
            self._admin_name = _srv.unique_source_name(self.name)
            _srv.add_registry(self._admin_name, self.metrics.registry)
            if self.tracer is not None:
                _srv.add_tracer(self._admin_name, self.tracer)
        # the batcher/finalizer pair is swapped atomically by revive()
        # and retired by stop(), both under the lifecycle lock; readers
        # (submit, queue_depth, alive) take the racy-by-design stale
        # reference — a put() into a just-retired batcher raises
        # ServiceClosed, which the caller already handles
        self._batcher = self._make_batcher()  # write-guarded-by: _lifecycle_lock
        # write-guarded-by: _lifecycle_lock
        self._finalizer = weakref.finalize(
            self, RequestBatcher.close, self._batcher, True, 5.0)
        if input_spec is not None:
            self.warmup(input_spec)
        if start:
            self._batcher.start()

    def _make_batcher(self) -> RequestBatcher:
        # a dropped service must not strand its batcher thread for the
        # life of the process (the historical PredictionService needed
        # no cleanup, so shim users never call stop()).  For the
        # finalizer to ever fire, the RUNNING thread must not pin the
        # service: the batcher gets a WeakMethod shim instead of the
        # bound `self._dispatch` (the ThreadPoolExecutor pattern) and
        # the finalize callback closes over the batcher only.  Corner
        # case (documented): a future whose service was garbage
        # collected before its dispatch resolves as cancelled — only
        # reachable by dropping every service reference while blocked
        # on result(), which predict() can't do (it holds `self`).
        weak_dispatch = weakref.WeakMethod(self._dispatch)

        def dispatch(requests):
            fn = weak_dispatch()
            if fn is None:  # service collected: nothing can resolve these
                for r in requests:
                    r.future.cancel()
                return
            fn(requests)

        return RequestBatcher(
            dispatch, max_batch_size=self.max_batch_size,
            batch_timeout_ms=self.batch_timeout_ms,
            queue_capacity=self.queue_capacity, name=self.name,
            priority_fn=self._priority_fn)

    # -- warmup ------------------------------------------------------------
    @staticmethod
    def _normalize_row_spec(input_spec):
        # a (shape, dtype) pair is a LEAF only when shape is a flat
        # tuple/list of ints — ``(((6,), f32), ((5,), f32))`` stays a
        # two-leaf pytree, not a shape of ((6,), f32)
        def is_pair(x):
            return (isinstance(x, tuple) and len(x) == 2
                    and isinstance(x[0], (tuple, list))
                    and all(isinstance(d, (int, np.integer))
                            for d in x[0]))

        def norm(leaf):
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return leaf
            if is_pair(leaf):
                return jax.ShapeDtypeStruct(tuple(leaf[0]),
                                            jnp.dtype(leaf[1]))
            arr = np.asarray(leaf)
            return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

        is_leaf = (lambda x: isinstance(x, (jax.ShapeDtypeStruct,
                                            np.ndarray)) or is_pair(x))
        return _tree.tree_map(norm, input_spec, is_leaf=is_leaf)

    def warmup(self, input_spec) -> dict:
        """AOT-compile every row bucket (idempotent).  Returns
        ``{bucket: compile_seconds}`` so deploy logs can record the
        warmup bill."""
        with self._warm_lock:
            # gate on the all-buckets-ready flag, NOT on _compiled
            # being non-empty: a concurrent submitter seeing a
            # partially-populated dict would dispatch into a KeyError
            if self._warmed:
                return {}
            row = self._normalize_row_spec(input_spec)
            # output row shape via abstract eval — no device work, runs
            # BEFORE any compile.  The coalescing contract REQUIRES
            # output rows to follow input rows (dispatch slices
            # per-request outputs by input-row offsets), so a model
            # whose output rows come from static metadata (COO
            # dense_shape, pooling-over-batch) must be refused at
            # deploy — without paying the bucket compile bill — not
            # silently mis-sliced per request; two probe sizes so a
            # coincidental match can't slip by.
            for k in (1, 2):
                speck = _tree.tree_map(
                    lambda s, _k=k: jax.ShapeDtypeStruct(
                        (_k,) + s.shape, s.dtype), row)
                out = jax.eval_shape(self._jit, self.params, self.state,
                                     speck)
                bad = [tuple(o.shape) for o in _tree.tree_leaves(out)
                       if o.shape[:1] != (k,)]
                if bad:
                    raise ValueError(
                        f"model {self.name!r} is not servable by the "
                        f"coalescing engine: output leading dims {bad} "
                        f"do not track the input batch dim ({k} rows "
                        "in) — per-request output slicing would return "
                        "garbage.  Serve it behind a custom batcher or "
                        "use Predictor for whole-dataset inference")
            self._row_spec = row
            timings = {}
            for b in self.buckets:
                spec = _tree.tree_map(
                    lambda s: jax.ShapeDtypeStruct((b,) + s.shape, s.dtype),
                    row)
                t0 = time.monotonic()
                # deploy-time compile DELIBERATELY under the warm lock:
                # serializing concurrent first-submitters until every
                # bucket executable exists is the warmup contract (a
                # half-warmed dict KeyErrors) — the one reviewed
                # blocking-under-lock exception in the serving stack
                # graftlint: disable=GL206
                self._compiled[b] = self._jit.lower(
                    self.params, self.state, spec).compile()
                timings[b] = round(time.monotonic() - t0, 4)
            self._out_spec = _tree.tree_map(
                lambda o: jax.ShapeDtypeStruct(tuple(o.shape[1:]), o.dtype),
                out)
            leaves = _tree.tree_leaves(self._out_spec)
            self._out_row_shape = (tuple(leaves[0].shape)
                                   if len(leaves) == 1 else None)
            self._warmed = True
            return timings

    @property
    def warmed_up(self) -> bool:
        return self._warmed

    @property
    def compile_count(self) -> int:
        """Python traces of the forward so far.  Frozen after warmup in
        steady state — the serving analog of the GL106 gate."""
        return self._trace_count

    def output_row_shape(self) -> Optional[Tuple[int, ...]]:
        """Trailing dims of one output row (known after warmup)."""
        return self._out_row_shape

    @property
    def row_spec(self):
        """The warmed per-row input spec (pytree of
        ``jax.ShapeDtypeStruct``), or None before warmup — reusable as
        another service's ``input_spec`` (ReplicaSet grow and hot
        cutover both warm new executables off this)."""
        return self._row_spec

    @property
    def drain_ewma_s(self) -> Optional[float]:
        """The batcher's observed seconds-per-request EWMA (None before
        its first dispatch) — the drain-rate signal ``retry_after_ms``
        hints and the frontend autoscaler read.  Racy-by-design single
        read of a single-writer float."""
        return self._batcher._spr_ewma

    # -- request path ------------------------------------------------------
    def _normalize_input(self, x):
        xs = _tree.tree_map(np.asarray, x)
        n = leading_rows(xs)
        return xs, n

    def _conform_request(self, xs):
        """Validate a request against the warmed row spec BEFORE it can
        join a coalesced group: a malformed request must fail alone at
        submit, not poison every innocent caller batched with it
        (np.concatenate would either raise for the whole group or
        silently promote everyone's dtype).  Trailing-shape or
        tree-structure mismatch raises; dtype mismatch is coerced to
        the spec dtype (the historical ``jnp.asarray`` behavior — e.g.
        a float64 numpy default quietly serves as f32)."""
        spec_leaves, spec_def = _tree.tree_flatten(self._row_spec)
        req_leaves, req_def = _tree.tree_flatten(xs)
        if spec_def != req_def or any(
                leaf.shape[1:] != tuple(s.shape)
                for leaf, s in zip(req_leaves, spec_leaves)):
            raise RequestSpecError(
                f"request does not match the deployed input_spec of "
                f"{self.name!r}: expected per-row "
                f"{[(tuple(s.shape), str(s.dtype)) for s in spec_leaves]}"
                f", got {[leaf.shape[1:] for leaf in req_leaves]}")
        try:
            conformed = [leaf if leaf.dtype == s.dtype
                         else np.asarray(leaf, dtype=s.dtype)
                         for leaf, s in zip(req_leaves, spec_leaves)]
        except (ValueError, TypeError) as e:
            # data the spec dtype refuses (e.g. strings into f32) is
            # the request's fault, same as a shape mismatch
            raise RequestSpecError(
                f"request data does not coerce to the deployed "
                f"input_spec dtypes of {self.name!r}: {e}") from None
        return _tree.tree_unflatten(req_def, conformed)

    def submit(self, x, *, deadline: Optional[float] = None,
               ctx=None) -> Future:
        """Enqueue one request (pytree of arrays, shared leading batch
        dim ``n`` with ``1 <= n <= max_batch_size``) and return the
        Future of its stacked outputs.  Raises
        :class:`ServiceOverloaded` when the bounded queue is full and
        :class:`ServiceClosed` after :meth:`stop`.

        ``deadline`` (absolute ``time.monotonic()`` seconds, or None)
        travels WITH the request through the queue: the dispatch path
        refuses expired work with :class:`DeadlineExceeded` instead of
        burning device time on a caller that has given up — the
        per-request deadline propagation ``ReplicaSet`` routes on.

        ``ctx`` is an optional :class:`~bigdl_tpu.telemetry.
        RequestContext`; with ``request_tracing`` on and ``ctx=None``
        one is minted here.  It rides the queue with the request — the
        dispatch span flow-links back to this submit's span, and a
        router appends its hop history."""
        xs, n = self._normalize_input(x)
        if n == 0:
            f: Future = Future()
            f.set_result(self._empty_output())
            return f
        if n > self.max_batch_size:
            raise RequestSpecError(
                f"request of {n} rows exceeds max_batch_size="
                f"{self.max_batch_size}; use predict() which chunks")
        if deadline is not None and time.monotonic() >= deadline:
            # already expired: resolve without ever touching the queue
            f = Future()
            f.set_exception(DeadlineExceeded(
                f"request deadline passed before submit to "
                f"{self.name!r}"))
            return f
        if not self._warmed:
            # deferred-spec path: capture the row spec from live
            # traffic (warmup is lock-idempotent, so concurrent first
            # requests all block until EVERY bucket is compiled)
            self.warmup(_tree.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), xs))
        xs = self._conform_request(xs)
        if ctx is None and self._request_tracing:
            from bigdl_tpu.telemetry.context import RequestContext
            ctx = RequestContext(deadline=deadline)
        req = _Request(xs, n, deadline=deadline, ctx=ctx)
        tracer = self.tracer
        if ctx is not None and tracer is not None and tracer.enabled:
            # the request's submit span, with the outbound half of the
            # fan-in flow arrow the dispatch span will close
            with tracer.span("request_submit", cat="serving",
                             trace_id=ctx.trace_id, model=self.name,
                             rows=n, tenant=ctx.tenant):
                tracer.flow_start("req", ctx.flow_id, cat="serving")
                self._put_counted(req, n)
        else:
            self._put_counted(req, n)
        return req.future

    def _put_counted(self, req: _Request, n: int) -> None:
        try:
            self._batcher.put(req)
        except ServiceOverloaded:
            self.metrics.record_reject(n)
            raise
        self.metrics.record_submit(n)

    def predict(self, x, timeout: Optional[float] = None):
        """Blocking sugar over :meth:`submit`; chunks inputs larger than
        ``max_batch_size`` across several coalescible requests.

        ``timeout`` bounds the WHOLE call (a shared deadline across
        chunk futures, not per-future).  Chunks are submitted through a
        bounded in-flight window (≤ half the queue capacity), so an
        arbitrarily large input never self-overflows the bounded queue
        the way a submit-everything loop would; overloads caused by
        *other* callers are absorbed by draining one in-flight chunk
        and retrying."""
        xs, n = self._normalize_input(x)
        if n == 0:
            return self._empty_output()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)

        def remaining() -> Optional[float]:
            if deadline is None:
                return None
            return max(0.0, deadline - time.monotonic())

        if n <= self.max_batch_size:
            return self.submit(xs).result(remaining())
        window = max(1, self.queue_capacity // 2)
        parts: List[Any] = []
        inflight: List[Future] = []
        for off in range(0, n, self.max_batch_size):
            lo, hi = off, off + self.max_batch_size
            chunk = _tree.tree_map(lambda a: a[lo:hi], xs)
            if len(inflight) >= window:
                parts.append(inflight.pop(0).result(remaining()))
            while True:
                try:
                    inflight.append(self.submit(chunk))
                    break
                except ServiceOverloaded:
                    if not inflight:  # foreign traffic owns the queue
                        raise
                    parts.append(inflight.pop(0).result(remaining()))
        parts.extend(f.result(remaining()) for f in inflight)
        return _tree.tree_map(
            lambda *ps: np.concatenate(ps, axis=0), *parts)

    def _empty_output(self):
        if self._out_spec is None:
            return np.empty((0,))
        return _tree.tree_map(
            lambda s: np.empty((0,) + tuple(s.shape), dtype=s.dtype),
            self._out_spec)

    # -- batcher callback --------------------------------------------------
    def _bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    def _dispatch(self, requests: List[_Request]) -> None:
        """Runs on the batcher thread: coalesce → pad to bucket → one
        compiled call → slice per-request outputs → resolve futures."""
        live = []
        for r in requests:
            try:
                if r.future.set_running_or_notify_cancel():
                    live.append(r)
            except Exception:
                # already resolved from OUTSIDE the batcher (the
                # ReplicaSet supervisor timing out / failing over a
                # stuck request) — nothing left to serve here
                pass
        if not live:
            return
        now = time.monotonic()
        expired = [r for r in live
                   if r.deadline is not None and now >= r.deadline]
        if expired:
            # deadline propagation: refuse expired work BEFORE the
            # device call — inference is idempotent, so the router may
            # already have retried it on another replica
            for r in expired:
                if settle_future(r.future, exc=DeadlineExceeded(
                        f"request expired in {self.name!r} queue after "
                        f"{(now - r.t_enqueue) * 1e3:.1f} ms")):
                    self.metrics.record_failure(r.n_rows)
            live = [r for r in live
                    if r.deadline is None or now < r.deadline]
            if not live:
                return
        rows = sum(r.n_rows for r in live)
        tracer = self.tracer
        ctxs = ([r.ctx for r in live if r.ctx is not None]
                if tracer is not None and tracer.enabled else [])
        if ctxs:
            # one dispatch span fanning in the N coalesced request
            # spans: each context's flow arrow (opened in its submit
            # span) is closed HERE, so Perfetto draws N arrows into
            # this slice; trace ids ride the span args for grepping
            with tracer.span("dispatch", cat="serving", model=self.name,
                             n_requests=len(live), rows=rows,
                             trace_ids=[c.trace_id for c in ctxs]):
                for c in ctxs:
                    tracer.flow_end("req", c.flow_id, cat="serving")
                self._dispatch_compiled(live, rows)
        else:
            self._dispatch_compiled(live, rows)

    def _dispatch_compiled(self, live: List[_Request], rows: int) -> None:
        try:
            if self._faults is not None:
                # fault site — inside the handler, so an injected
                # dispatch error resolves the group's futures like any
                # real dispatch failure; ReplicaDeathFault is a
                # BaseException and ESCAPES, killing this batcher
                # thread with the group stranded, exactly like a real
                # thread crash (the failure the ReplicaSet supervisor
                # exists to detect)
                ix = self._dispatch_index
                self._dispatch_index += 1
                self._faults.serving_dispatch(ix, self._fault_replica)
            if len(live) == 1:
                x = live[0].x
            else:
                x = _tree.tree_map(
                    lambda *leaves: np.concatenate(leaves, axis=0),
                    *[r.x for r in live])
            bucket = self._bucket_for(rows)
            x = pad_rows(x, bucket)
            out = _tree.tree_map(
                np.asarray,
                self._compiled[bucket](self.params, self.state, x))
            # defense in depth behind the warmup rows-track gate: never
            # slice per-request offsets out of an output whose leading
            # dim is not the dispatched bucket — fail the group loudly
            bad = [o.shape for o in _tree.tree_leaves(out)
                   if o.shape[:1] != (bucket,)]
            if bad:
                raise RuntimeError(
                    f"output leading dims {bad} != bucket {bucket}; "
                    "refusing to slice per-request results")
            self.metrics.record_dispatch(rows, bucket)
            now = time.monotonic()
            off = 0
            for r in live:
                lo, hi = off, off + r.n_rows
                if settle_future(r.future, result=_tree.tree_map(
                        lambda o: o[lo:hi], out)):
                    # counted only when THIS dispatch settled it — a
                    # straggler completing a request the supervisor
                    # already failed over must not double-count it
                    self.metrics.record_done(r.n_rows,
                                             now - r.t_enqueue,
                                             bucket=bucket)
                off = hi
        except Exception as e:  # resolve, never strand, the waiters
            for r in live:
                if not r.future.done():
                    if settle_future(r.future, exc=e):
                        self.metrics.record_failure(r.n_rows)

    # -- stats / lifecycle -------------------------------------------------
    @property
    def alive(self) -> bool:
        """False once the batcher thread has DIED without an orderly
        stop — a crashed dispatch (or an injected ``ReplicaDeathFault``)
        took it down, so accepted work can no longer dispatch.  A parked
        (``start=False``, not yet started) service counts as alive: it
        can still be started.  This is the liveness predicate the
        ``ReplicaSet`` supervisor polls."""
        return not self._stopped and not self._batcher.dead

    def revive(self) -> bool:
        """Replace a DEAD batcher thread with a fresh one over the SAME
        warmed bucket executables — no recompile, params untouched, the
        service keeps its name/metrics.  The dead batcher's stranded
        backlog is cancelled first (its futures are typically already
        failed over by the ``ReplicaSet`` supervisor).  No-op (returns
        False) while the current batcher is healthy; raises
        :class:`ServiceClosed` after :meth:`stop`."""
        with self._lifecycle_lock:
            if self._stopped:
                raise ServiceClosed(
                    f"cannot revive stopped service {self.name!r}")
            if not self._batcher.dead:
                return False
            cancelled = self._batcher.close(drain=False, timeout=1.0)
            if cancelled:
                self.metrics.record_cancel(cancelled)
            self._finalizer.detach()
            self._batcher = self._make_batcher()
            self._finalizer = weakref.finalize(
                self, RequestBatcher.close, self._batcher, True, 5.0)
            self._batcher.start()
            return True

    @property
    def last_progress(self) -> Optional[float]:
        """Monotonic time of the batcher's last completed dispatch (or
        its start; None before either) — the liveness signal the
        ``ReplicaSet`` supervisor uses to tell a WEDGED replica from a
        merely congested one."""
        return self._batcher.last_progress

    def queue_depth(self) -> int:
        return self._batcher.depth()

    def stats(self) -> dict:
        """Snapshot dict — schema documented in README "serving"."""
        snap = self.metrics.snapshot(queue_depth=self._batcher.depth(),
                                     compile_count=self._trace_count)
        snap["model"] = self.name
        snap["max_batch_size"] = self.max_batch_size
        snap["buckets"] = list(self.buckets)
        return snap

    def start(self) -> None:
        with self._lifecycle_lock:
            self._batcher.start()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Graceful shutdown: refuse new submits, drain (default) or
        cancel the backlog, join the batcher.  Idempotent."""
        with self._lifecycle_lock:
            if self._stopped:
                return
            self._stopped = True
            self._finalizer.detach()
            cancelled_rows = self._batcher.close(drain=drain,
                                                 timeout=timeout)
        if cancelled_rows:
            self.metrics.record_cancel(cancelled_rows)
        # a stopped service must not linger on the admin plane (its
        # metrics would be pinned forever and a redeploy under the
        # same name expects a clean slot)
        if self._admin_name is not None:
            from bigdl_tpu.telemetry import admin as _admin
            _srv = _admin.current()
            if _srv is not None:
                _srv.remove_source(self._admin_name)

    def release(self) -> None:
        """Drop params/state/bucket executables of a STOPPED service so
        a retired replica slot stops pinning device memory until it is
        reused (``ReplicaSet.set_replica_count`` shrink path).  Refuses
        on a live service — the batcher thread still dispatches through
        these references."""
        if not self._stopped:
            raise RuntimeError(
                f"release() on live service {self.name!r}; stop() first")
        self.params = None
        self.state = None
        with self._warm_lock:
            self._compiled = {}
            self._warmed = False
            self._row_spec = None

    def __enter__(self) -> "InferenceService":
        return self

    def __exit__(self, *exc) -> None:
        self.stop(drain=True)
