"""Bounded request queue + coalescing batcher thread.

Reference: BigDL 2.0 Cluster Serving's Flink pipeline pops *batches* of
queued requests off Redis streams so one forward serves many callers
(arXiv:2204.01715 §3.2); TensorFlow-Serving calls the same idea dynamic
batching.  The TPU-native translation: a single batcher thread owns the
device dispatch, coalescing whatever concurrent callers have enqueued —
up to ``max_batch_size`` rows, waiting at most ``batch_timeout_ms`` after
the first request — into ONE bucket-padded executable call.

Design rules:

- **Bounded queue = explicit backpressure.**  ``put`` never blocks and
  never grows unboundedly: a full queue raises
  :class:`ServiceOverloaded` (carrying the observed depth) so the edge
  can shed load / retry with jitter instead of silently queueing into
  timeout territory.
- **Event-driven.**  One ``Condition`` covers producers and the batcher;
  there are no polling sleeps anywhere (tests rely on this — they pause
  and resume the batcher deterministically).
- **Drain-then-stop shutdown.**  ``close(drain=True)`` refuses new work
  but the batcher keeps dispatching until the queue is empty, so every
  accepted future resolves; ``drain=False`` cancels what is still
  queued.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence


class ServiceOverloaded(RuntimeError):
    """Bounded request queue is full — shed load upstream.

    Carries ``queue_depth`` / ``capacity`` so callers (and error pages)
    can report how far behind the service is, and ``retry_after_ms`` —
    an estimate (from the batcher's observed queue drain rate) of when
    the queue will have room again, so shed callers can back off a
    useful amount instead of guessing.  ``None`` when the batcher has
    not dispatched anything yet.
    """

    def __init__(self, queue_depth: int, capacity: int, model: str = "",
                 retry_after_ms: Optional[float] = None):
        self.queue_depth = queue_depth
        self.capacity = capacity
        self.model = model
        self.retry_after_ms = retry_after_ms
        tag = f" model={model!r}" if model else ""
        hint = (f"; retry_after_ms={retry_after_ms:.1f}"
                if retry_after_ms is not None else "")
        super().__init__(
            f"serving queue full{tag}: depth={queue_depth} "
            f"capacity={capacity}{hint} — backpressure; retry with "
            f"backoff or raise queue_capacity")


class ServiceClosed(RuntimeError):
    """submit() after close() — the service no longer accepts work."""


class RequestSpecError(ValueError):
    """The REQUEST's shape is wrong: it does not conform to the
    deployed ``input_spec`` (tree structure / trailing-shape mismatch)
    or exceeds ``max_batch_size``.  Raised synchronously by ``submit``
    so a malformed request fails alone instead of poisoning the batch
    it would have coalesced into.  Subclasses ``ValueError`` for
    backward compatibility; the distinct type lets callers (the wire
    frontend's 400 mapping) tell caller-fault validation apart from an
    internal ``ValueError``, which stays a server-side bug."""


def settle_future(fut: Future, *, result=None,
                  exc: Optional[BaseException] = None) -> bool:
    """Resolve a request future, tolerating the race where someone
    else got there first (a late batcher completion vs. the ReplicaSet
    supervisor timing out or failing over the same request).  Returns
    whether THIS call settled it — callers gate their per-request
    accounting on that, so a request served after being failed over is
    not double-counted.  The ONE such helper; service.py and
    resilience/replica_set.py both use it."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
        return True
    except Exception:  # InvalidStateError: already resolved — benign
        return False


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before (or while) it could be
    served.  Set on the request's future by the dispatch path (expired
    work is refused before the device call) or by an outside supervisor
    (work stuck on a dead/wedged replica).  Inference is idempotent, so
    a router may retry the same request elsewhere."""


class _Request:
    """One enqueued inference request: a pytree of np arrays with a
    shared leading row dim ``n_rows`` (≤ max_batch_size, enforced by the
    service) plus the future the caller is waiting on.  ``deadline``
    (monotonic seconds, or None) travels WITH the request through the
    queue — the dispatch path refuses expired work.  ``ctx`` is the
    optional :class:`~bigdl_tpu.telemetry.context.RequestContext`
    (trace_id / tenant / hop history) riding the same journey — None
    (the default) is the provably-inert state."""

    __slots__ = ("x", "n_rows", "future", "t_enqueue", "deadline", "ctx")

    def __init__(self, x, n_rows: int, deadline: Optional[float] = None,
                 ctx=None):
        self.x = x
        self.n_rows = n_rows
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()
        self.deadline = deadline
        self.ctx = ctx


class RequestBatcher:
    """The queue and the thread that drains it.

    ``dispatch_fn(requests)`` — supplied by
    :class:`~bigdl_tpu.serving.InferenceService` — performs the coalesced
    device call and resolves each request's future.  The batcher
    guarantees: each accepted request is handed to ``dispatch_fn``
    exactly once (or cancelled on non-drain shutdown), coalesced groups
    never exceed ``max_batch_size`` total rows, and after the first
    request of a group arrives the group waits at most
    ``batch_timeout_ms`` before dispatch.

    ``batch_timeout_ms=0`` is *adaptive* batching: a group is whatever
    is ALREADY queued when the batcher comes around (the previous
    dispatch's latency is the natural coalescing window) — lone
    sequential callers dispatch immediately instead of eating the
    timeout, while concurrent load still coalesces.  The
    ``PredictionService`` shim runs in this mode to preserve its
    historical immediate-dispatch latency.

    ``priority_fn`` is the QoS preemption hook (the frontend's
    per-tenant admission layer supplies it): a callable mapping an
    enqueued :class:`_Request` to an int rank (lower dispatches
    first).  It engages ONLY under pressure — when the queued rows
    exceed what one ``max_batch_size`` dispatch can carry — because
    under light load every queued request rides the same coalesced
    group anyway and FIFO order costs nothing.  Under pressure the
    collect loop picks the best-(effective rank, arrival) request
    that still fits, so latency-class tenants preempt batch-class
    backlog; equal ranks stay FIFO.  Starvation is BOUNDED by aging:
    a queued request's effective rank improves by one class per
    ``priority_aging_ms`` waited, so sustained latency-class
    saturation delays batch work by at most ~one aging period per
    class gap instead of indefinitely.  ``None`` (the default) is
    byte-identical to the pre-hook batcher.
    """

    def __init__(self, dispatch_fn: Callable[[List[_Request]], None],
                 *, max_batch_size: int, batch_timeout_ms: float,
                 queue_capacity: int, name: str = "serving",
                 priority_fn: Optional[Callable[["_Request"], int]] = None,
                 priority_aging_ms: float = 500.0):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1: {max_batch_size}")
        if queue_capacity < 1:
            raise ValueError(f"queue_capacity must be >= 1: {queue_capacity}")
        self._dispatch_fn = dispatch_fn
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_s = float(batch_timeout_ms) / 1e3
        self.queue_capacity = int(queue_capacity)
        self._name = name
        self._priority_fn = priority_fn
        self._priority_aging_s = max(1e-3, priority_aging_ms / 1e3)

        self._cond = threading.Condition()
        self._q: deque[_Request] = deque()  # guarded-by: _cond
        # running total of queued ROWS — kept in lockstep with _q so
        # the QoS pressure test is O(1) per pop instead of re-summing
        # the deque (O(queue_len) per pop is quadratic per dispatch
        # exactly when the queue is full); guarded-by: _cond.  Every
        # inc/dec is `# acquires:`/`# releases:`-tagged so GL303 keeps
        # the pairing checkable (a pop path that forgets the decrement
        # desynchronizes the QoS pressure signal forever).
        self._q_rows = 0
        self._closed = False                # guarded-by: _cond
        self._drain = True                  # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None
        self.cancelled_rows = 0
        # EWMA of seconds-per-request through dispatch, written only by
        # the batcher thread (reads are racy-by-design: a hint, not an
        # invariant) — feeds ServiceOverloaded.retry_after_ms
        self._spr_ewma: Optional[float] = None
        # monotonic time of the last completed dispatch (or start()) —
        # the liveness signal an outside supervisor uses to tell a
        # WEDGED batcher (no progress) from a congested one (draining,
        # just slower than the deadline).  Racy-by-design single write.
        self.last_progress: Optional[float] = None

    # -- producer side -----------------------------------------------------
    def retry_after_ms(self, depth: Optional[int] = None) -> Optional[float]:
        """How long (ms) until the current backlog should have drained,
        from the observed dispatch rate.  None before the first
        dispatch (no rate to estimate from)."""
        spr = self._spr_ewma
        if spr is None:
            return None
        if depth is None:
            # racy-by-design depth sample: a retry hint, not an
            # invariant (put() passes the locked depth in)
            depth = len(self._q)  # graftlint: disable=GL201
        return round(min(max(depth * spr * 1e3, 1.0), 10_000.0), 1)

    def _note_dispatch(self, n_requests: int, elapsed_s: float) -> None:
        spr = elapsed_s / max(1, n_requests)
        prev = self._spr_ewma
        self._spr_ewma = spr if prev is None else 0.7 * prev + 0.3 * spr
        self.last_progress = time.monotonic()

    def put(self, req: _Request) -> None:
        with self._cond:
            if self._closed:
                raise ServiceClosed(
                    f"serving endpoint {self._name!r} is stopped")
            if len(self._q) >= self.queue_capacity:
                depth = len(self._q)
                raise ServiceOverloaded(
                    depth, self.queue_capacity, self._name,
                    retry_after_ms=self.retry_after_ms(depth))
            self._q.append(req)
            self._q_rows += req.n_rows  # acquires: queue_rows
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Idempotent; tests construct services with ``start=False`` to
        stage a queue deterministically before the first dispatch.
        Concurrent callers must hold the service lifecycle lock (they
        do: InferenceService.start/revive)."""
        if self._thread is None:
            # pre-start write: Thread.start() is the happens-before
            # edge, so the batcher thread observes it without a lock
            self.last_progress = time.monotonic()  # graftlint: disable=GL201
            thread = threading.Thread(
                target=self._run, name=f"{self._name}-batcher", daemon=True)
            thread.start()
            # published only AFTER start(): a created-but-unstarted
            # thread reads as is_alive()=False, and an outside liveness
            # poll (the ReplicaSet supervisor) hitting that microsecond
            # window would misread a healthy parked replica as DEAD and
            # fail over its whole queue (caught by the elasticity tests
            # staging parked sets under a live supervisor)
            self._thread = thread

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def dead(self) -> bool:
        """The batcher thread was started and has DIED without
        ``close()`` — a crashed dispatch (or an injected
        ``ReplicaDeathFault``) took it down, so queued work can no
        longer dispatch.  Distinct from ``running=False`` before
        ``start()`` (a parked batcher can still be started) and from a
        closed batcher (an orderly stop is not a death).  This is the
        liveness the ``ReplicaSet`` supervisor polls."""
        # lock-free liveness sample BY DESIGN: the supervisor polls this
        # from outside; a stale read just delays detection one poll
        return (self._thread is not None
                and not self._thread.is_alive()
                and not self._closed)  # graftlint: disable=GL201

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> int:
        """Refuse new work; drain (default) or cancel the backlog; join
        the batcher thread.  Safe to call twice, and safe to call on a
        never-started batcher (the backlog is then resolved inline).
        Returns the number of ROWS cancelled (0 when draining)."""
        with self._cond:
            was_dead = self.dead
            self._closed = True
            self._drain = drain
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            if was_dead or not self._thread.is_alive():
                # a CRASHED batcher can neither drain nor cancel its
                # own backlog, and inline-dispatching on the caller's
                # thread could re-raise whatever killed it — cancel the
                # remainder so no accepted future is left dangling
                # (no-op after an orderly drain: the queue is empty)
                self._cancel_backlog()
            return self.cancelled_rows
        # batcher never ran: resolve the backlog on the caller's
        # thread so no accepted future is left dangling
        if drain:
            self._drain_inline()
            return 0
        return self._cancel_backlog()

    def _cancel_backlog(self) -> int:
        rows = 0
        while True:
            with self._cond:
                if not self._q:
                    self.cancelled_rows += rows
                    return rows
                req = self._q.popleft()
                self._q_rows -= req.n_rows  # releases: queue_rows
            if req.future.cancel():
                rows += req.n_rows

    def _drain_inline(self) -> None:
        while True:
            batch = self._collect(block=False)
            if not batch:
                return
            self._dispatch_fn(batch)

    def _dispatch_timed(self, batch: List[_Request]) -> None:
        t0 = time.monotonic()
        try:
            self._dispatch_fn(batch)
        finally:
            self._note_dispatch(len(batch), time.monotonic() - t0)

    # -- batcher thread ----------------------------------------------------
    def _run(self) -> None:
        drain = True
        while True:
            batch = self._collect(block=True)
            if batch:
                self._dispatch_timed(batch)
                continue
            # empty collect while blocking only happens when closed
            with self._cond:
                if self._closed and (not self._drain or not self._q):
                    drain = self._drain  # captured under the lock
                    break
        if not drain:
            self._cancel_backlog()

    # guarded-by: _cond
    def _rank_locked(self, req: _Request, now: float) -> int:
        """Effective QoS rank of one queued request: the declared rank
        minus one class per aging period waited (the starvation bound
        — a batch-class request that has queued ``priority_aging_ms``
        competes as latency class).  A broken priority_fn ranks as 0
        (most urgent) instead of killing the batcher thread."""
        try:
            rank = int(self._priority_fn(req))
        except Exception:
            return 0
        return rank - int((now - req.t_enqueue)
                          / self._priority_aging_s)

    # guarded-by: _cond
    def _pop_next_locked(self, rows: int) -> Optional[_Request]:
        """Pop the next request for the current group, or None when the
        candidate doesn't fit under ``max_batch_size``.  FIFO
        (head-or-nothing — the historical contract) except under QoS
        pressure: with a ``priority_fn`` set AND more rows queued than
        one dispatch can carry, the best-(rank, arrival) request that
        still fits is taken instead, so latency-class tenants preempt
        batch backlog exactly when ordering starts to matter."""
        if not self._q:
            return None
        pressure = (self._priority_fn is not None and len(self._q) > 1
                    and rows + self._q_rows > self.max_batch_size)
        if not pressure:
            if self._q[0].n_rows + rows > self.max_batch_size:
                return None
            req = self._q.popleft()
            self._q_rows -= req.n_rows  # releases: queue_rows
            return req
        best_i, best_key = -1, None
        now = time.monotonic()
        for i, r in enumerate(self._q):
            if r.n_rows + rows > self.max_batch_size:
                continue
            # arrival ix = FIFO tie-break within an effective rank
            key = (self._rank_locked(r, now), i)
            if best_key is None or key < best_key:
                best_key, best_i = key, i
        if best_i < 0:
            return None  # nothing queued fits in the remaining rows
        req = self._q[best_i]
        del self._q[best_i]
        self._q_rows -= req.n_rows  # releases: queue_rows
        return req

    def _collect(self, block: bool) -> List[_Request]:
        """Pop one coalescible group: wait (if ``block``) for the first
        request, then keep taking requests that fit under
        ``max_batch_size`` rows until the timeout since the first pop
        expires or the next candidate doesn't fit."""
        batch: List[_Request] = []
        rows = 0
        with self._cond:
            while block and not self._q and not self._closed:
                self._cond.wait()
            if self._closed and not self._drain:
                return batch  # backlog is _run's to CANCEL, not pop
            first = self._pop_next_locked(0)
            if first is None:
                return batch
            batch.append(first)
            rows = first.n_rows
            deadline = time.monotonic() + self.batch_timeout_s
            while rows < self.max_batch_size:
                nxt = self._pop_next_locked(rows)
                if nxt is not None:
                    batch.append(nxt)
                    rows += nxt.n_rows
                    continue
                if self._q:
                    break  # queued work doesn't fit this group
                if self._closed:
                    break  # draining: don't wait for traffic that won't come
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    break
        return batch
