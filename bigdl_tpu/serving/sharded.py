"""ShardedReplicaSet — a ReplicaSet whose replica is a MESH SLICE.

ROADMAP item 1: today a replica is exactly one device, so a model that
does not fit one chip cannot be served at all.  Here a replica slot owns
``devices_per_replica`` devices arranged as a named
:class:`~jax.sharding.Mesh` (``parallel/mesh.py``), and the replica's
params are ``device_put`` leaf-by-leaf with the
:class:`~jax.sharding.NamedSharding` the model's own ``param_specs``
opt-ins declare (``parallel/tensor_parallel.py`` —
``Linear(shard="column"/"row")``, ``MultiHeadAttention(shard=True)``;
the SNIPPETS NamedSharding weight-placement pattern: "8-chip pods to
6000-chip superclusters without changing application code").  GSPMD
inserts the collectives around the split matmuls; nothing here writes
communication by hand.

Everything else is INHERITED from :class:`~bigdl_tpu.resilience.
ReplicaSet`: least-queue-depth routing, health/quarantine/failover,
elastic ``set_replica_count`` (a grown mesh-slice replica AOT-warms its
bucket ladder off the routing path), ``stats()`` aggregation, and the
``submit()``-shaped contract — so ``FrontendServer.add_backend``,
:class:`~bigdl_tpu.frontend.HotCutover`, the
:class:`~bigdl_tpu.frontend.ReplicaAutoscaler` and ``/metrics`` all work
at mesh-slice granularity with zero frontend changes (the frontend's
``isinstance(backend, ReplicaSet)`` dispatch sees this subclass).

Device partitioning: the device list is cut into consecutive groups of
``devices_per_replica``; slot ``ix`` takes group ``ix % n_groups``, so —
like the base class — more replicas than device groups is legal
(emulated replicas share a group round-robin, the CPU-host test rig).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from bigdl_tpu.resilience.health import ReplicaHealth
from bigdl_tpu.resilience.replica_set import ReplicaSet
from bigdl_tpu.serving.service import InferenceService


class ShardedReplicaSet(ReplicaSet):
    """:class:`ReplicaSet` with N-device mesh-slice replicas.

    Parameters beyond the base class:

    - ``devices_per_replica``: devices per slot (the mesh-slice size).
      ``devices`` must supply at least one full group.
    - ``mesh_axes``: axis-name → size dict for the per-slot mesh
      (default ``{"model": devices_per_replica}`` — pure tensor
      parallelism).  Axis sizes must multiply to
      ``devices_per_replica``; unnamed axes default to 1.  Axis names
      follow ``parallel/mesh.py`` (``data``/``model``/``seq``/``pipe``).

    ``n_replicas`` defaults to the number of COMPLETE device groups
    (``len(devices) // devices_per_replica``), not the device count.
    """

    def __init__(self, model, params=None, state=None, *,
                 devices_per_replica: int = 2,
                 mesh_axes: Optional[Dict[str, int]] = None,
                 n_replicas: Optional[int] = None,
                 devices: Optional[Sequence] = None, **kw):
        import jax
        if devices is None:
            devices = jax.local_devices()
        devices = list(devices)
        dpr = int(devices_per_replica)
        if dpr < 1:
            raise ValueError(f"devices_per_replica must be >= 1: {dpr}")
        n_groups = len(devices) // dpr
        if n_groups < 1:
            raise ValueError(
                f"need at least {dpr} devices for one mesh-slice "
                f"replica, have {len(devices)}")
        axes = dict(mesh_axes) if mesh_axes else {"model": dpr}
        bad = set(axes) - {"data", "model", "seq", "pipe"}
        if bad:
            raise ValueError(f"unknown mesh axes {sorted(bad)}")
        size = 1
        for v in axes.values():
            size *= int(v)
        if size != dpr:
            raise ValueError(
                f"mesh axes {axes} multiply to {size}, need "
                f"devices_per_replica={dpr}")
        # set BEFORE super().__init__ — the base constructor calls
        # _build_replica (overridden below) for every initial slot
        self.devices_per_replica = dpr
        self._mesh_axes = axes
        self._groups = [devices[g * dpr:(g + 1) * dpr]
                        for g in range(n_groups)]
        if n_replicas is None:
            n_replicas = n_groups
        super().__init__(model, params, state, n_replicas=n_replicas,
                         devices=devices, **kw)

    # ---------------------------------------------------- replica build
    def replica_mesh(self, ix: int):
        """The (already-built) mesh of slot ``ix``'s service, or a fresh
        one for a not-yet-built slot — introspection surface for tests
        and ops tooling."""
        svc = self._replicas[ix] if ix < len(self._replicas) else None
        mesh = getattr(svc, "_mesh", None)
        return mesh if mesh is not None else self._slot_mesh(ix)

    def _slot_mesh(self, ix: int):
        from bigdl_tpu.parallel.mesh import create_mesh
        group = self._groups[ix % len(self._groups)]
        ax = self._mesh_axes
        return create_mesh(data=ax.get("data", 1),
                           model=ax.get("model", 1),
                           seq=ax.get("seq", 1),
                           pipe=ax.get("pipe", 1), devices=group)

    def _build_replica(self, ix: int, input_spec):
        """Mesh-slice twin of the base builder: instead of committing
        params onto ONE device, build slot ``ix``'s named mesh over its
        device group and ``device_put`` every param leaf with the
        NamedSharding its module declared (replicated ``P()`` for
        non-opt-ins).  The replica's jit then follows its params'
        shardings — GSPMD compiles the collectives into the bucket
        executables during the SAME off-path AOT warmup the base class
        does, so a grown mesh-slice replica never serves a compile (or
        collective-layout) stall."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.parallel.tensor_parallel import build_param_specs
        mesh = self._slot_mesh(ix)
        specs = build_param_specs(self._model, self._base_params)
        p_i = jax.tree_util.tree_map(
            lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
            self._base_params, specs)
        s_i = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P())),
            self._base_state)
        svc = InferenceService(
            self._model, p_i, s_i, input_spec=input_spec,
            workload=self._workload, name=f"{self.name}/r{ix}",
            start=self._started, fault_injector=self._faults,
            tracer=self.tracer,
            request_tracing=self._request_tracing,
            priority_fn=self._priority_fn, **self._service_kw)
        svc._fault_replica = ix
        svc._mesh = mesh  # introspection (replica_mesh, tests)
        health = ReplicaHealth(ix, policy=self._policy,
                               registry=self.registry,
                               recorder=self._flight)
        return svc, health
