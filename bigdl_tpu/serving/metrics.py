"""Serving observability: per-model counters + latency percentiles.

Reference: BigDL 2.0 Cluster Serving exposes per-model throughput/latency
through its dashboard (arXiv:2204.01715 §4); the reference
``PredictionService.scala`` tracks nothing but a request count.  Here every
:class:`~bigdl_tpu.serving.InferenceService` owns one :class:`ServingMetrics`
and surfaces it as a plain-dict snapshot (``service.stats()``) so callers can
ship it to whatever metrics sink they run.

Since the telemetry PR the backing store is the unified
:class:`bigdl_tpu.telemetry.registry.MetricRegistry` (counters +
reservoir histograms) — the same substrate the training driver and the
runtime watchdogs use.  ``LatencyReservoir`` is the registry
:class:`~bigdl_tpu.telemetry.registry.Reservoir` (kept under its
historical name for back-compat).

Latency reservoirs are keyed TWO ways: one global window (the historical
surface) and one per row-bucket — a 1-row dispatch and a 32-row-bucket
dispatch have very different service times, and the global p99 hides
which bucket is paying it (ROADMAP serving item 1c).  Bucket reservoirs
appear lazily as traffic exercises each bucket.

Everything is host-side bookkeeping — nothing here touches jax.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from bigdl_tpu.telemetry.registry import MetricRegistry, Reservoir

# back-compat alias: the serving latency window IS the registry reservoir
LatencyReservoir = Reservoir


class ServingMetrics:
    """Thread-safe counters for one deployed model.

    ``mean_batch_occupancy`` is real rows / dispatched (bucket) rows —
    1.0 means every padded slot carried a real request, 1/bucket means
    the batcher is dispatching singletons (no coalescing win).
    """

    def __init__(self, registry: Optional[MetricRegistry] = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        reg = self.registry
        self._submitted = reg.counter("serving/requests_submitted")
        self._completed = reg.counter("serving/requests_completed")
        self._rejected = reg.counter("serving/requests_rejected")
        self._failed = reg.counter("serving/requests_failed")
        self._cancelled = reg.counter("serving/requests_cancelled")
        self._dispatches = reg.counter("serving/dispatches")
        self._rows_real = reg.counter("serving/rows_real")
        self._rows_dispatched = reg.counter("serving/rows_dispatched")
        self.latency = LatencyReservoir()
        # per-row-bucket latency windows, created as buckets see traffic
        self._bucket_latency: Dict[int, Reservoir] = {}

    # back-compat value surface (pre-registry these were plain ints)
    @property
    def submitted(self) -> int:
        return self._submitted.value

    @property
    def completed(self) -> int:
        return self._completed.value

    @property
    def rejected(self) -> int:
        return self._rejected.value

    @property
    def failed(self) -> int:
        return self._failed.value

    @property
    def cancelled(self) -> int:
        return self._cancelled.value

    @property
    def dispatches(self) -> int:
        return self._dispatches.value

    @property
    def rows_real(self) -> int:
        return self._rows_real.value

    @property
    def rows_dispatched(self) -> int:
        return self._rows_dispatched.value

    # -- recording (called from submit / batcher threads) -----------------
    def record_submit(self, rows: int) -> None:
        self._submitted.inc(rows)

    def record_reject(self, rows: int = 1) -> None:
        self._rejected.inc(rows)

    def record_dispatch(self, real_rows: int, bucket_rows: int) -> None:
        self._dispatches.inc()
        self._rows_real.inc(real_rows)
        self._rows_dispatched.inc(bucket_rows)

    def record_done(self, rows: int, latency_s: float,
                    bucket: Optional[int] = None) -> None:
        self._completed.inc(rows)
        self.latency.record(latency_s)
        if bucket is not None:
            res = self._bucket_latency.get(bucket)
            if res is None:
                with self._lock:  # lazy get-or-create, race-safe
                    res = self._bucket_latency.setdefault(
                        bucket, LatencyReservoir())
            res.record(latency_s)

    def record_failure(self, rows: int) -> None:
        self._failed.inc(rows)

    def record_cancel(self, rows: int) -> None:
        self._cancelled.inc(rows)

    # -- snapshot ----------------------------------------------------------
    @staticmethod
    def _ms(pct: Optional[dict]) -> Optional[dict]:
        if pct is None:
            return None
        return {k: round(v * 1e3, 3) for k, v in pct.items()}

    def snapshot(self, queue_depth: int = 0,
                 compile_count: int = 0) -> dict:
        """Plain-dict stats (the ``service.stats()`` schema documented in
        the README serving section).  Latencies are reported in ms."""
        elapsed = max(time.monotonic() - self.started_at, 1e-9)
        rows_dispatched = self.rows_dispatched
        occ = (self.rows_real / rows_dispatched
               if rows_dispatched else None)
        snap = {
            "requests_submitted": self.submitted,
            "requests_completed": self.completed,
            "requests_rejected": self.rejected,
            "requests_failed": self.failed,
            "requests_cancelled": self.cancelled,
            "dispatch_count": self.dispatches,
            "rows_dispatched": rows_dispatched,
            "mean_batch_occupancy":
                round(occ, 4) if occ is not None else None,
            "throughput_rps": round(self.completed / elapsed, 2),
            "queue_depth": queue_depth,
            "compile_count": compile_count,
            "uptime_s": round(elapsed, 3),
        }
        snap["latency_ms"] = self._ms(self.latency.percentiles())
        with self._lock:
            buckets = sorted(self._bucket_latency.items())
        snap["latency_ms_by_bucket"] = (
            {b: self._ms(r.percentiles()) for b, r in buckets}
            if buckets else None)
        return snap
