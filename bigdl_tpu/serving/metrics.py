"""Serving observability: per-model counters + latency percentiles.

Reference: BigDL 2.0 Cluster Serving exposes per-model throughput/latency
through its dashboard (arXiv:2204.01715 §4); the reference
``PredictionService.scala`` tracks nothing but a request count.  Here every
:class:`~bigdl_tpu.serving.InferenceService` owns one :class:`ServingMetrics`
and surfaces it as a plain-dict snapshot (``service.stats()``) so callers can
ship it to whatever metrics sink they run.

Everything is host-side bookkeeping — nothing here touches jax.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class LatencyReservoir:
    """Fixed-size ring of recent request latencies (seconds).

    A bounded ring instead of an unbounded list: an always-on endpoint
    must not grow memory with request count.  Percentiles are computed
    over the retained window (the most recent ``capacity`` requests),
    which is the standard sliding-window SLO estimator.
    """

    def __init__(self, capacity: int = 4096):
        self._buf = [0.0] * capacity
        self._n = 0          # total ever recorded
        self._lock = threading.Lock()

    def record(self, latency_s: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = latency_s
            self._n += 1

    def percentiles(self, qs=(50, 95, 99)) -> Optional[Dict[str, float]]:
        with self._lock:
            n = min(self._n, len(self._buf))
            if n == 0:
                return None
            window = sorted(self._buf[:n])
        out = {}
        for q in qs:
            # nearest-rank percentile over the window
            idx = min(n - 1, max(0, int(round(q / 100.0 * n)) - 1))
            out[f"p{q}"] = window[idx]
        out["mean"] = sum(window) / n
        out["max"] = window[-1]
        return out


class ServingMetrics:
    """Thread-safe counters for one deployed model.

    ``mean_batch_occupancy`` is real rows / dispatched (bucket) rows —
    1.0 means every padded slot carried a real request, 1/bucket means
    the batcher is dispatching singletons (no coalescing win).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.cancelled = 0
        self.dispatches = 0
        self.rows_real = 0       # rows carrying actual requests
        self.rows_dispatched = 0  # bucket rows sent to the device
        self.latency = LatencyReservoir()

    # -- recording (called from submit / batcher threads) -----------------
    def record_submit(self, rows: int) -> None:
        with self._lock:
            self.submitted += rows

    def record_reject(self, rows: int = 1) -> None:
        with self._lock:
            self.rejected += rows

    def record_dispatch(self, real_rows: int, bucket_rows: int) -> None:
        with self._lock:
            self.dispatches += 1
            self.rows_real += real_rows
            self.rows_dispatched += bucket_rows

    def record_done(self, rows: int, latency_s: float) -> None:
        with self._lock:
            self.completed += rows
        self.latency.record(latency_s)

    def record_failure(self, rows: int) -> None:
        with self._lock:
            self.failed += rows

    def record_cancel(self, rows: int) -> None:
        with self._lock:
            self.cancelled += rows

    # -- snapshot ----------------------------------------------------------
    def snapshot(self, queue_depth: int = 0,
                 compile_count: int = 0) -> dict:
        """Plain-dict stats (the ``service.stats()`` schema documented in
        the README serving section).  Latencies are reported in ms."""
        with self._lock:
            elapsed = max(time.monotonic() - self.started_at, 1e-9)
            occ = (self.rows_real / self.rows_dispatched
                   if self.rows_dispatched else None)
            snap = {
                "requests_submitted": self.submitted,
                "requests_completed": self.completed,
                "requests_rejected": self.rejected,
                "requests_failed": self.failed,
                "requests_cancelled": self.cancelled,
                "dispatch_count": self.dispatches,
                "rows_dispatched": self.rows_dispatched,
                "mean_batch_occupancy":
                    round(occ, 4) if occ is not None else None,
                "throughput_rps": round(self.completed / elapsed, 2),
                "queue_depth": queue_depth,
                "compile_count": compile_count,
                "uptime_s": round(elapsed, 3),
            }
        pct = self.latency.percentiles()
        snap["latency_ms"] = (
            {k: round(v * 1e3, 3) for k, v in pct.items()}
            if pct else None)
        return snap
