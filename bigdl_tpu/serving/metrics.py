"""Serving observability: per-model counters + latency percentiles.

Reference: BigDL 2.0 Cluster Serving exposes per-model throughput/latency
through its dashboard (arXiv:2204.01715 §4); the reference
``PredictionService.scala`` tracks nothing but a request count.  Here every
:class:`~bigdl_tpu.serving.InferenceService` owns one :class:`ServingMetrics`
and surfaces it as a plain-dict snapshot (``service.stats()``) so callers can
ship it to whatever metrics sink they run.

Since the telemetry PR the backing store is the unified
:class:`bigdl_tpu.telemetry.registry.MetricRegistry` (counters +
reservoir histograms) — the same substrate the training driver and the
runtime watchdogs use.  Since the admin-plane PR the latency windows are
registry **histograms** (``serving/latency_s`` global,
``serving/latency_s_bucket{N}`` per row bucket), so a ``/metrics``
scrape renders their quantiles with zero extra bookkeeping;
``LatencyReservoir`` is still the registry
:class:`~bigdl_tpu.telemetry.registry.Reservoir` and the historical
``.latency`` attribute is the global histogram's backing reservoir —
the pre-registry surface keeps working.

Latency reservoirs are keyed TWO ways: one global window (the historical
surface) and one per row-bucket — a 1-row dispatch and a 32-row-bucket
dispatch have very different service times, and the global p99 hides
which bucket is paying it (ROADMAP serving item 1c).  Bucket reservoirs
appear lazily as traffic exercises each bucket.

Window-bias audit (the admin-plane PR): ``throughput_rps`` used to be
``completed / uptime`` — a service snapshot taken after traffic stopped
(or a ReplicaSet replica that idled while its siblings served) diluted
the rate with idle time.  It is now computed over the ACTIVITY window
(first submit → last completion); ``throughput_window_s`` reports the
window so readers can tell a 1 s burst from a 10 s steady state, and
:meth:`ServingMetrics.aggregate` computes the set-level view over the
union of the replicas' activity windows instead of summing per-replica
rates with mismatched denominators (regression-gated in
``tests/test_obs_plane.py``).

Everything is host-side bookkeeping — nothing here touches jax.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from bigdl_tpu.telemetry.registry import (Histogram, MetricRegistry,
                                          Reservoir)

# back-compat alias: the serving latency window IS the registry reservoir
LatencyReservoir = Reservoir


class ServingMetrics:
    """Thread-safe counters for one deployed model.

    ``mean_batch_occupancy`` is real rows / dispatched (bucket) rows —
    1.0 means every padded slot carried a real request, 1/bucket means
    the batcher is dispatching singletons (no coalescing win).
    """

    def __init__(self, registry: Optional[MetricRegistry] = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self._lock = threading.Lock()
        self.started_at = time.monotonic()
        reg = self.registry
        self._submitted = reg.counter("serving/requests_submitted")
        self._completed = reg.counter("serving/requests_completed")
        self._rejected = reg.counter("serving/requests_rejected")
        self._failed = reg.counter("serving/requests_failed")
        self._cancelled = reg.counter("serving/requests_cancelled")
        self._dispatches = reg.counter("serving/dispatches")
        self._rows_real = reg.counter("serving/rows_real")
        self._rows_dispatched = reg.counter("serving/rows_dispatched")
        # global latency window: a registry histogram so /metrics
        # renders its quantiles; .latency is its backing reservoir (the
        # historical attribute surface)
        self._latency_h = reg.histogram("serving/latency_s")
        self.latency = self._latency_h.reservoir
        # per-row-bucket latency histograms, created as buckets see
        # traffic (registry get-or-create is atomic; the lock only
        # guards the local cache dict); guarded-by: _lock
        self._bucket_latency: Dict[int, Histogram] = {}
        # activity window (monotonic): first submit → last completion —
        # the unbiased throughput denominator (module docstring)
        self._t_first_submit: Optional[float] = None
        self._t_last_done: Optional[float] = None
        # weights dtype of the served model (int8 speed-path PR): the
        # gauge is PRE-created here — one fixed metric name per service
        # registry, value-coded — so the Prometheus scrape schema is
        # bounded up front instead of growing a label per dtype string.
        # Snapshot back-compat: the "weights_dtype" key appears only
        # once set (absent = "f32", the historical default).
        self._weights_dtype: Optional[str] = None
        self._weights_dtype_g = reg.gauge("serving/weights_dtype_code")

    #: fixed value coding for serving/weights_dtype_code (absent
    #: dtypes intentionally unrepresentable — bounded cardinality)
    WEIGHTS_DTYPE_CODES = {"f32": 0, "bf16": 1, "int8": 2}

    def set_weights_dtype(self, dtype: str) -> None:
        """Tag the served model's weight dtype (``"f32"`` | ``"bf16"``
        | ``"int8"``) — surfaces in :meth:`snapshot` and as the
        pre-created ``serving/weights_dtype_code`` gauge on
        ``/metrics``."""
        if dtype not in self.WEIGHTS_DTYPE_CODES:
            raise ValueError(
                f"weights_dtype must be one of "
                f"{sorted(self.WEIGHTS_DTYPE_CODES)}, got {dtype!r}")
        self._weights_dtype = dtype
        self._weights_dtype_g.set(self.WEIGHTS_DTYPE_CODES[dtype])

    @property
    def weights_dtype(self) -> Optional[str]:
        return self._weights_dtype

    # back-compat value surface (pre-registry these were plain ints)
    @property
    def submitted(self) -> int:
        return self._submitted.value

    @property
    def completed(self) -> int:
        return self._completed.value

    @property
    def rejected(self) -> int:
        return self._rejected.value

    @property
    def failed(self) -> int:
        return self._failed.value

    @property
    def cancelled(self) -> int:
        return self._cancelled.value

    @property
    def dispatches(self) -> int:
        return self._dispatches.value

    @property
    def rows_real(self) -> int:
        return self._rows_real.value

    @property
    def rows_dispatched(self) -> int:
        return self._rows_dispatched.value

    # -- recording (called from submit / batcher threads) -----------------
    def record_submit(self, rows: int) -> None:
        if self._t_first_submit is None:
            # racy-by-design single write: two first submits land
            # within microseconds of each other — either anchors fine
            self._t_first_submit = time.monotonic()
        self._submitted.inc(rows)

    def record_reject(self, rows: int = 1) -> None:
        self._rejected.inc(rows)

    def record_dispatch(self, real_rows: int, bucket_rows: int) -> None:
        self._dispatches.inc()
        self._rows_real.inc(real_rows)
        self._rows_dispatched.inc(bucket_rows)

    def record_done(self, rows: int, latency_s: float,
                    bucket: Optional[int] = None) -> None:
        self._completed.inc(rows)
        self._t_last_done = time.monotonic()
        self._latency_h.observe(latency_s)
        if bucket is not None:
            # lock-free fast-path read BY DESIGN: a GIL-atomic dict get
            # racing the locked setdefault below at worst misses and
            # falls into the locked path; record_done is per-request
            # hot — graftlint: disable=GL201
            h = self._bucket_latency.get(bucket)
            if h is None:
                with self._lock:  # lazy get-or-create, race-safe
                    h = self._bucket_latency.setdefault(
                        bucket, self.registry.histogram(
                            f"serving/latency_s_bucket{bucket}"))
            h.observe(latency_s)

    def record_failure(self, rows: int) -> None:
        self._failed.inc(rows)

    def record_cancel(self, rows: int) -> None:
        self._cancelled.inc(rows)

    # -- windows -----------------------------------------------------------
    def activity_window(self) -> Optional[tuple]:
        """(first_submit, last_done) monotonic pair, or None before any
        completion — the unbiased throughput denominator."""
        t0, t1 = self._t_first_submit, self._t_last_done
        if t0 is None or t1 is None:
            return None
        return (t0, max(t1, t0))

    # -- snapshot ----------------------------------------------------------
    @staticmethod
    def _ms(pct: Optional[dict]) -> Optional[dict]:
        if pct is None:
            return None
        return {k: round(v * 1e3, 3) for k, v in pct.items()}

    def snapshot(self, queue_depth: int = 0,
                 compile_count: int = 0) -> dict:
        """Plain-dict stats (the ``service.stats()`` schema documented in
        the README serving section).  Latencies are reported in ms."""
        uptime = max(time.monotonic() - self.started_at, 1e-9)
        window = self.activity_window()
        window_s = max(window[1] - window[0], 1e-9) if window else None
        completed = self.completed
        rows_dispatched = self.rows_dispatched
        occ = (self.rows_real / rows_dispatched
               if rows_dispatched else None)
        snap = {
            "requests_submitted": self.submitted,
            "requests_completed": completed,
            "requests_rejected": self.rejected,
            "requests_failed": self.failed,
            "requests_cancelled": self.cancelled,
            "dispatch_count": self.dispatches,
            "rows_dispatched": rows_dispatched,
            "mean_batch_occupancy":
                round(occ, 4) if occ is not None else None,
            # rate over the ACTIVITY window, not uptime (window-bias
            # audit in the module docstring); 0.0 before any completion
            "throughput_rps": (round(completed / window_s, 2)
                               if window_s is not None else 0.0),
            "throughput_window_s": (round(window_s, 3)
                                    if window_s is not None else None),
            "queue_depth": queue_depth,
            "compile_count": compile_count,
            "uptime_s": round(uptime, 3),
        }
        if self._weights_dtype is not None:
            snap["weights_dtype"] = self._weights_dtype
        snap["latency_ms"] = self._ms(self._latency_h.percentiles())
        with self._lock:
            buckets = sorted(self._bucket_latency.items())
        snap["latency_ms_by_bucket"] = (
            {b: self._ms(h.percentiles()) for b, h in buckets}
            if buckets else None)
        return snap

    # -- set-level aggregation --------------------------------------------
    @staticmethod
    def aggregate(metrics: Sequence["ServingMetrics"],
                  queue_depth: int = 0) -> dict:
        """Snapshot-shaped aggregate over N per-replica metrics (the
        ``ReplicaSet.stats()["aggregate"]`` view — satellite audit):

        - counters sum;
        - ``throughput_rps`` = total completions over the UNION of the
          replicas' activity windows (earliest first-submit → latest
          completion) — not a sum of per-replica rates, whose
          denominators differ, and not replica 0's number;
        - latency percentiles are computed over the CONCATENATED
          reservoir windows (global and per bucket), so the set p99 is
          the p99 of actual recent samples, not an average of averages.
        """
        metrics = list(metrics)  # tolerate one-shot iterables
        tot = {k: 0 for k in
               ("requests_submitted", "requests_completed",
                "requests_rejected", "requests_failed",
                "requests_cancelled", "dispatch_count",
                "rows_real", "rows_dispatched")}
        windows: List[tuple] = []
        lat_samples: List[float] = []
        bucket_samples: Dict[int, List[float]] = {}
        for m in metrics:
            tot["requests_submitted"] += m.submitted
            tot["requests_completed"] += m.completed
            tot["requests_rejected"] += m.rejected
            tot["requests_failed"] += m.failed
            tot["requests_cancelled"] += m.cancelled
            tot["dispatch_count"] += m.dispatches
            tot["rows_real"] += m.rows_real
            tot["rows_dispatched"] += m.rows_dispatched
            w = m.activity_window()
            if w is not None:
                windows.append(w)
            lat_samples.extend(m.latency.window())
            with m._lock:
                items = list(m._bucket_latency.items())
            for b, h in items:
                bucket_samples.setdefault(b, []).extend(
                    h.reservoir.window())
        window_s = (max(w[1] for w in windows)
                    - min(w[0] for w in windows)) if windows else None
        if window_s is not None:
            window_s = max(window_s, 1e-9)
        occ = (tot["rows_real"] / tot["rows_dispatched"]
               if tot["rows_dispatched"] else None)

        def pct(samples: List[float]) -> Optional[dict]:
            # same nearest-rank rule as Reservoir.percentiles, computed
            # directly over the already-materialized sample list
            n = len(samples)
            window = sorted(samples)
            out_ = {}
            for q in (50, 95, 99):
                idx = min(n - 1, max(0, int(round(q / 100.0 * n)) - 1))
                out_[f"p{q}"] = window[idx]
            out_["mean"] = sum(window) / n
            out_["max"] = window[-1]
            return ServingMetrics._ms(out_)

        out = dict(tot)
        out.pop("rows_real")
        out["n_sources"] = len(metrics)
        out["mean_batch_occupancy"] = (round(occ, 4)
                                       if occ is not None else None)
        out["throughput_rps"] = (
            round(tot["requests_completed"] / window_s, 2)
            if window_s is not None else 0.0)
        out["throughput_window_s"] = (round(window_s, 3)
                                      if window_s is not None else None)
        out["queue_depth"] = queue_depth
        out["latency_ms"] = pct(lat_samples) if lat_samples else None
        out["latency_ms_by_bucket"] = (
            {b: pct(s) for b, s in sorted(bucket_samples.items())}
            if bucket_samples else None)
        return out
