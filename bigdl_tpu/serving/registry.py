"""ModelRegistry — named/versioned deployment of InferenceServices.

Reference: BigDL 2.0 Cluster Serving deploys models by name into a
shared cluster and routes by model id (arXiv:2204.01715 §3.1); the
reference mono-model ``PredictionService.scala`` has no registry at all.
Here one registry process hosts many models, each behind its own
:class:`~bigdl_tpu.serving.InferenceService` (own queue, own buckets,
own stats), deployable either from an in-memory Module or straight from
the interop wire formats (BigDL / Caffe / TF / Keras / Torch — the same
loaders ``interop.convert_model`` uses), optionally int8-quantized via
``nn.quantized.quantize`` on the way in.

Resilience: every deployed version carries a
:class:`~bigdl_tpu.resilience.health.CircuitBreaker`.  Latest-wins
routing consults it — ``breaker_trip_after`` consecutive request
failures on the newest version open its breaker and un-versioned
``get``/``predict``/``submit`` calls fall back to the newest version
whose breaker still admits traffic, so a poisoned deploy stops eating
the error budget within a handful of requests instead of until a human
rolls back.  After ``breaker_cooldown_s`` the tripped version goes
half-open: the next routed request is its trial (success closes the
breaker, failure re-trips with a doubled cooldown).  Overload/closed
rejections are never counted — a full queue says nothing about whether
the model is poisoned.  Pinned ``version=`` requests bypass the breaker
(the caller asked for that version, they get its errors).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.resilience.health import CircuitBreaker
from bigdl_tpu.serving.batcher import ServiceClosed, ServiceOverloaded
from bigdl_tpu.serving.service import InferenceService

logger = logging.getLogger("bigdl_tpu.serving")


def _load_model(fmt: str, path: str, *, prototxt: Optional[str] = None,
                weights: Optional[str] = None,
                tf_inputs: Optional[List[str]] = None,
                tf_outputs: Optional[List[str]] = None):
    """Load a model from an interop wire format (mirror of
    ``interop.convert_model._load``, keyword-driven)."""
    fmt = fmt.lower()
    if fmt == "bigdl":
        from bigdl_tpu.interop import load_bigdl_module
        return load_bigdl_module(path)
    if fmt == "caffe":
        if not prototxt:
            raise ValueError("format='caffe' requires prototxt=")
        from bigdl_tpu.interop import load_caffe_model
        return load_caffe_model(prototxt, path)
    if fmt == "torch":
        from bigdl_tpu.interop.torch_export import load_torch_module
        return load_torch_module(path)
    if fmt in ("tf", "tensorflow"):
        if not (tf_inputs and tf_outputs):
            raise ValueError(
                "format='tensorflow' requires tf_inputs= and tf_outputs=")
        from bigdl_tpu.interop import load_tf_graph
        return load_tf_graph(path, inputs=tf_inputs, outputs=tf_outputs)
    if fmt == "keras":
        from bigdl_tpu.interop import load_keras_json
        model = load_keras_json(path)
        if weights:
            from bigdl_tpu.interop import load_keras_hdf5_weights
            load_keras_hdf5_weights(model, weights)
        return model.core_module()
    raise ValueError(f"unknown serving model format {fmt!r}; expected "
                     "bigdl|caffe|torch|tensorflow|keras")


class ModelRegistry:
    """Thread-safe name → version → service map.

    ``deploy`` auto-increments the version per name (or takes an
    explicit one); ``get``/``predict`` default to the newest version so
    rolling upgrades are deploy-new-then-undeploy-old with no caller
    change.  ``undeploy`` drains the service before dropping it.
    """

    def __init__(self, *, breaker_trip_after: int = 5,
                 breaker_cooldown_s: float = 30.0, registry=None,
                 flight=None):
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._services: Dict[Tuple[str, int], InferenceService] = {}
        self._latest: Dict[str, int] = {}  # guarded-by: _lock
        # keys mid-deploy (reserved before the slow AOT warmup)
        self._pending: set[Tuple[str, int]] = set()  # guarded-by: _lock
        # per-version circuit breakers (see module docstring); the
        # optional MetricRegistry receives resilience/breaker_trips and
        # resilience/breaker_fallbacks counters
        self._breaker_trip_after = int(breaker_trip_after)
        self._breaker_cooldown_s = float(breaker_cooldown_s)
        self._metrics = registry
        # guarded-by: _lock
        self._breakers: Dict[Tuple[str, int], CircuitBreaker] = {}
        # flight recorder (telemetry round 2): breaker trips and
        # latest-wins fallbacks land there so a post-mortem sees WHICH
        # deploy was poisoned and when routing moved off it.  None —
        # the inert state — unless Config.flight_recorder_path is set
        # or a recorder is passed explicitly.
        from bigdl_tpu.telemetry import flight as _flight_mod
        self._flight = flight if flight is not None \
            else _flight_mod.from_config()
        # admin plane: breaker states as a /healthz source (ok = no
        # breaker currently open).  The name is made unique so two
        # registries in one process don't overwrite each other.
        from bigdl_tpu.telemetry import admin as _admin
        self._admin_name: Optional[str] = None
        _srv = _admin.maybe_start()
        if _srv is not None:
            self._admin_name = _srv.unique_source_name("model_registry")
            _srv.add_health(self._admin_name, self.breaker_health)

    # -- deployment --------------------------------------------------------
    def deploy(self, name: str, model=None, *, path: Optional[str] = None,
               format: Optional[str] = None, version: Optional[int] = None,
               params=None, state=None, quantize=False,
               prototxt: Optional[str] = None,
               weights: Optional[str] = None,
               tf_inputs: Optional[List[str]] = None,
               tf_outputs: Optional[List[str]] = None,
               service=None, **service_kw) -> InferenceService:
        """Deploy ``model`` (or load one from ``path``/``format``) as
        ``name``:``version``.  ``service_kw`` flows to
        :class:`InferenceService` (``input_spec`` for deploy-time AOT
        warmup, batching/backpressure knobs, ``start=False``...).

        ``service=``: register an ALREADY-CONSTRUCTED submit()-shaped
        backend (e.g. a :class:`~bigdl_tpu.serving.DecodeService`)
        under latest-wins + breaker routing instead of building an
        :class:`InferenceService` — hot cutover and undeploy work
        unchanged (they only need ``stop(drain=)``).  Mutually
        exclusive with ``model``/``path``/``service_kw``.

        ``quantize``: False (default) deploys as-is; True int8-quantizes
        on the way in with the ``Config.int8_activation_mode`` default;
        a mode string (``"weight_only"`` / ``"dynamic"``) pins the
        activation mode.  The quantized deploy is a DISTINCT registry
        version with its own circuit breaker and a ``weights_dtype``
        stats tag — latest-wins routing plus the breaker gate rollback
        to the float incumbent if the int8 version misbehaves."""
        if service is not None:
            if model is not None or path is not None or service_kw:
                raise ValueError(
                    "deploy(service=) takes a prebuilt backend — "
                    "model/path/service_kw don't apply")
        elif model is None:
            if path is None or format is None:
                raise ValueError("deploy() needs model= or path=+format=")
            model = _load_model(format, path, prototxt=prototxt,
                                weights=weights, tf_inputs=tf_inputs,
                                tf_outputs=tf_outputs)
        if quantize:
            from bigdl_tpu.nn.quantized import quantize as _quantize
            # quantize=True -> config-default mode; a string pins it
            q_mode = quantize if isinstance(quantize, str) else None
            model = _quantize(model, mode=q_mode)
            params = state = None  # quantized twin re-owns its weights
        # reserve the (name, version) key BEFORE the (slow, lock-free)
        # AOT warmup in the service constructor: two concurrent deploys
        # must not pick the same auto-version and silently overwrite
        # (orphaning the loser's batcher thread)
        with self._lock:
            if version is None:
                pending = [v for (n, v) in self._pending if n == name]
                version = max([self._latest.get(name, 0), *pending]) + 1
            key = (name, int(version))
            if key in self._services or key in self._pending:
                raise ValueError(
                    f"model {name!r} version {version} already deployed; "
                    "undeploy it first or bump the version")
            self._pending.add(key)  # acquires: deploy_reservation
        if service is None:
            try:
                service = InferenceService(
                    model, params, state, name=f"{name}:v{version}",
                    **service_kw)
            except BaseException:
                with self._lock:
                    self._pending.discard(key)  # releases: deploy_reservation
                raise
        with self._lock:
            self._pending.discard(key)  # releases: deploy_reservation
            self._services[key] = service
            self._breakers[key] = CircuitBreaker(
                trip_after=self._breaker_trip_after,
                cooldown_s=self._breaker_cooldown_s,
                registry=self._metrics, name=f"{name}:v{version}",
                recorder=self._flight)
            self._latest[name] = max(self._latest.get(name, 0),
                                     int(version))
        return service

    # -- lookup ------------------------------------------------------------
    # guarded-by: _lock
    def _resolve(self, name: str, version: Optional[int]) -> Tuple[str, int]:
        """Caller must hold ``self._lock`` (so error paths below must
        not re-take it — ``self._lock`` is not reentrant).

        Latest-wins routing (``version=None``) consults the per-version
        circuit breakers: versions are tried newest-first and the first
        whose breaker admits traffic wins, so a poisoned newest deploy
        falls back to the previous version while its breaker cools
        down.  When EVERY breaker is open the newest version is used
        anyway — serving a maybe-poisoned model beats serving nothing,
        and its next failure just re-trips."""
        if version is None:
            if name not in self._latest:
                raise KeyError(f"no model {name!r} deployed; have "
                               f"{sorted(self._latest)}")
            newest = self._latest[name]
            version = newest
            for v in sorted((v for (n, v) in self._services if n == name),
                            reverse=True):
                brk = self._breakers.get((name, v))
                if brk is None or brk.allow():
                    version = v
                    break
            if version != newest:
                if self._metrics is not None:
                    self._metrics.counter(
                        "resilience/breaker_fallbacks").inc()
                if self._flight is not None:
                    self._flight.record(
                        "breaker_fallback", cat="resilience",
                        model=name, from_version=newest,
                        to_version=version)
                logger.warning(
                    "model %r v%d breaker open — routing to v%d",
                    name, newest, version)
        key = (name, int(version))
        if key not in self._services:
            have = sorted(v for (n, v) in self._services if n == name)
            raise KeyError(f"model {name!r} has no version {version}; "
                           f"deployed: {have}")
        return key

    def route(self, name: str, version: Optional[int] = None
              ) -> Tuple[int, InferenceService,
                         Optional[CircuitBreaker]]:
        """Resolve one request's destination: ``(resolved_version,
        service, breaker)``.  The wire frontend routes through this —
        it needs the RESOLVED version (latest-wins + breaker fallback)
        pinned for the whole wire exchange (a multi-chunk streaming
        predict must not straddle a hot cutover) and the breaker to
        feed the outcome back via :meth:`record_outcome`."""
        with self._lock:
            key = self._resolve(name, version)
            return key[1], self._services[key], self._breakers.get(key)

    def _routed(self, name: str, version: Optional[int]):
        _v, svc, brk = self.route(name, version)
        return svc, brk

    def latest_version(self, name: str) -> Optional[int]:
        """Newest deployed version of ``name`` (no breaker consult), or
        None when the name has no deployments — what a hot cutover
        reads BEFORE deploying to know which version it must drain."""
        with self._lock:
            return self._latest.get(name)

    @staticmethod
    def record_outcome(brk: Optional[CircuitBreaker],
                       exc: Optional[BaseException]) -> None:
        """Feed one request outcome to the served version's breaker.
        Overload/closed rejections say nothing about model poisoning
        (documented breaker contract) — they are not recorded at all.
        Public because external routers (the wire frontend) that pin a
        version via :meth:`route` owe the breaker the same feedback
        the in-process paths give it."""
        if brk is None:
            return
        if exc is None:
            brk.record_success()
        elif not isinstance(exc, (ServiceOverloaded, ServiceClosed)):
            brk.record_failure()

    def get(self, name: str,
            version: Optional[int] = None) -> InferenceService:
        with self._lock:
            return self._services[self._resolve(name, version)]

    def predict(self, name: str, x, version: Optional[int] = None,
                timeout: Optional[float] = None):
        svc, brk = self._routed(name, version)
        try:
            out = svc.predict(x, timeout=timeout)
        except BaseException as e:
            self.record_outcome(brk, e)
            raise
        self.record_outcome(brk, None)
        return out

    def submit(self, name: str, x, version: Optional[int] = None):
        svc, brk = self._routed(name, version)
        fut = svc.submit(x)  # an overload raises here — never recorded
        # a CANCELLED future is no outcome at all: the version never
        # served the request, so it earns neither a success (which
        # would reset a poisoned deploy's failure streak) nor a failure
        fut.add_done_callback(
            lambda f, _b=brk: None if f.cancelled()
            else self.record_outcome(_b, f.exception()))
        return fut

    def breaker_state(self, name: str, version: int) -> dict:
        """Snapshot of one version's circuit breaker (tests/dashboards)."""
        with self._lock:
            return self._breakers[(name, int(version))].snapshot()

    def breaker_health(self) -> dict:
        """The ``/healthz`` provider: every deployed version's breaker
        snapshot; ``ok`` = no breaker currently open."""
        with self._lock:
            breakers = dict(self._breakers)
        snaps = {f"{n}:v{v}": brk.snapshot()
                 for (n, v), brk in sorted(breakers.items())}
        return {"ok": not any(s["open"] for s in snaps.values()),
                "breakers": snaps}

    def list_models(self) -> Dict[str, List[int]]:
        with self._lock:
            out: Dict[str, List[int]] = {}
            for (n, v) in self._services:
                out.setdefault(n, []).append(v)
            return {n: sorted(vs) for n, vs in out.items()}

    # -- teardown ----------------------------------------------------------
    def undeploy(self, name: str, version: Optional[int] = None,
                 drain: bool = True) -> None:
        """Stop (drain by default) and drop one version — or every
        version of ``name`` when ``version`` is None."""
        with self._lock:
            if version is None:
                keys = [k for k in self._services if k[0] == name]
                if not keys:
                    raise KeyError(f"no model {name!r} deployed")
            else:
                keys = [self._resolve(name, version)]
            doomed = [self._services.pop(k) for k in keys]
            for k in keys:
                self._breakers.pop(k, None)
            remaining = [v for (n, v) in self._services if n == name]
            if remaining:
                self._latest[name] = max(remaining)
            else:
                self._latest.pop(name, None)
        for svc in doomed:
            svc.stop(drain=drain)

    def stats(self) -> Dict[str, dict]:
        """``{"name:vN": service-stats}`` across every deployment — the
        registry-wide snapshot a metrics scraper exports."""
        with self._lock:
            services = dict(self._services)
            breakers = dict(self._breakers)
        return {f"{n}:v{v}": {**svc.stats(),
                              "breaker": breakers[(n, v)].snapshot()
                              if (n, v) in breakers else None}
                for (n, v), svc in sorted(services.items())}

    def stop_all(self, drain: bool = True) -> None:
        with self._lock:
            services = list(self._services.values())
            self._services.clear()
            self._breakers.clear()
            self._latest.clear()
        for svc in services:
            svc.stop(drain=drain)
        if self._admin_name is not None:
            from bigdl_tpu.telemetry import admin as _admin
            _srv = _admin.current()
            if _srv is not None:
                _srv.remove_source(self._admin_name)

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.stop_all(drain=True)
