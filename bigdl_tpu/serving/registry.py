"""ModelRegistry — named/versioned deployment of InferenceServices.

Reference: BigDL 2.0 Cluster Serving deploys models by name into a
shared cluster and routes by model id (arXiv:2204.01715 §3.1); the
reference mono-model ``PredictionService.scala`` has no registry at all.
Here one registry process hosts many models, each behind its own
:class:`~bigdl_tpu.serving.InferenceService` (own queue, own buckets,
own stats), deployable either from an in-memory Module or straight from
the interop wire formats (BigDL / Caffe / TF / Keras / Torch — the same
loaders ``interop.convert_model`` uses), optionally int8-quantized via
``nn.quantized.quantize`` on the way in.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.serving.service import InferenceService


def _load_model(fmt: str, path: str, *, prototxt: Optional[str] = None,
                weights: Optional[str] = None,
                tf_inputs: Optional[List[str]] = None,
                tf_outputs: Optional[List[str]] = None):
    """Load a model from an interop wire format (mirror of
    ``interop.convert_model._load``, keyword-driven)."""
    fmt = fmt.lower()
    if fmt == "bigdl":
        from bigdl_tpu.interop import load_bigdl_module
        return load_bigdl_module(path)
    if fmt == "caffe":
        if not prototxt:
            raise ValueError("format='caffe' requires prototxt=")
        from bigdl_tpu.interop import load_caffe_model
        return load_caffe_model(prototxt, path)
    if fmt == "torch":
        from bigdl_tpu.interop.torch_export import load_torch_module
        return load_torch_module(path)
    if fmt in ("tf", "tensorflow"):
        if not (tf_inputs and tf_outputs):
            raise ValueError(
                "format='tensorflow' requires tf_inputs= and tf_outputs=")
        from bigdl_tpu.interop import load_tf_graph
        return load_tf_graph(path, inputs=tf_inputs, outputs=tf_outputs)
    if fmt == "keras":
        from bigdl_tpu.interop import load_keras_json
        model = load_keras_json(path)
        if weights:
            from bigdl_tpu.interop import load_keras_hdf5_weights
            load_keras_hdf5_weights(model, weights)
        return model.core_module()
    raise ValueError(f"unknown serving model format {fmt!r}; expected "
                     "bigdl|caffe|torch|tensorflow|keras")


class ModelRegistry:
    """Thread-safe name → version → service map.

    ``deploy`` auto-increments the version per name (or takes an
    explicit one); ``get``/``predict`` default to the newest version so
    rolling upgrades are deploy-new-then-undeploy-old with no caller
    change.  ``undeploy`` drains the service before dropping it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._services: Dict[Tuple[str, int], InferenceService] = {}
        self._latest: Dict[str, int] = {}
        # keys mid-deploy (reserved before the slow AOT warmup)
        self._pending: set[Tuple[str, int]] = set()

    # -- deployment --------------------------------------------------------
    def deploy(self, name: str, model=None, *, path: Optional[str] = None,
               format: Optional[str] = None, version: Optional[int] = None,
               params=None, state=None, quantize: bool = False,
               prototxt: Optional[str] = None,
               weights: Optional[str] = None,
               tf_inputs: Optional[List[str]] = None,
               tf_outputs: Optional[List[str]] = None,
               **service_kw) -> InferenceService:
        """Deploy ``model`` (or load one from ``path``/``format``) as
        ``name``:``version``.  ``service_kw`` flows to
        :class:`InferenceService` (``input_spec`` for deploy-time AOT
        warmup, batching/backpressure knobs, ``start=False``...)."""
        if model is None:
            if path is None or format is None:
                raise ValueError("deploy() needs model= or path=+format=")
            model = _load_model(format, path, prototxt=prototxt,
                                weights=weights, tf_inputs=tf_inputs,
                                tf_outputs=tf_outputs)
        if quantize:
            from bigdl_tpu.nn.quantized import quantize as _quantize
            model = _quantize(model)
            params = state = None  # quantized twin re-owns its weights
        # reserve the (name, version) key BEFORE the (slow, lock-free)
        # AOT warmup in the service constructor: two concurrent deploys
        # must not pick the same auto-version and silently overwrite
        # (orphaning the loser's batcher thread)
        with self._lock:
            if version is None:
                pending = [v for (n, v) in self._pending if n == name]
                version = max([self._latest.get(name, 0), *pending]) + 1
            key = (name, int(version))
            if key in self._services or key in self._pending:
                raise ValueError(
                    f"model {name!r} version {version} already deployed; "
                    "undeploy it first or bump the version")
            self._pending.add(key)
        try:
            service = InferenceService(
                model, params, state, name=f"{name}:v{version}",
                **service_kw)
        except BaseException:
            with self._lock:
                self._pending.discard(key)
            raise
        with self._lock:
            self._pending.discard(key)
            self._services[key] = service
            self._latest[name] = max(self._latest.get(name, 0),
                                     int(version))
        return service

    # -- lookup ------------------------------------------------------------
    def _resolve(self, name: str, version: Optional[int]) -> Tuple[str, int]:
        """Caller must hold ``self._lock`` (so error paths below must
        not re-take it — ``self._lock`` is not reentrant)."""
        if version is None:
            if name not in self._latest:
                raise KeyError(f"no model {name!r} deployed; have "
                               f"{sorted(self._latest)}")
            version = self._latest[name]
        key = (name, int(version))
        if key not in self._services:
            have = sorted(v for (n, v) in self._services if n == name)
            raise KeyError(f"model {name!r} has no version {version}; "
                           f"deployed: {have}")
        return key

    def get(self, name: str,
            version: Optional[int] = None) -> InferenceService:
        with self._lock:
            return self._services[self._resolve(name, version)]

    def predict(self, name: str, x, version: Optional[int] = None,
                timeout: Optional[float] = None):
        return self.get(name, version).predict(x, timeout=timeout)

    def submit(self, name: str, x, version: Optional[int] = None):
        return self.get(name, version).submit(x)

    def list_models(self) -> Dict[str, List[int]]:
        with self._lock:
            out: Dict[str, List[int]] = {}
            for (n, v) in self._services:
                out.setdefault(n, []).append(v)
            return {n: sorted(vs) for n, vs in out.items()}

    # -- teardown ----------------------------------------------------------
    def undeploy(self, name: str, version: Optional[int] = None,
                 drain: bool = True) -> None:
        """Stop (drain by default) and drop one version — or every
        version of ``name`` when ``version`` is None."""
        with self._lock:
            if version is None:
                keys = [k for k in self._services if k[0] == name]
                if not keys:
                    raise KeyError(f"no model {name!r} deployed")
            else:
                keys = [self._resolve(name, version)]
            doomed = [self._services.pop(k) for k in keys]
            remaining = [v for (n, v) in self._services if n == name]
            if remaining:
                self._latest[name] = max(remaining)
            else:
                self._latest.pop(name, None)
        for svc in doomed:
            svc.stop(drain=drain)

    def stats(self) -> Dict[str, dict]:
        """``{"name:vN": service-stats}`` across every deployment — the
        registry-wide snapshot a metrics scraper exports."""
        with self._lock:
            services = dict(self._services)
        return {f"{n}:v{v}": svc.stats()
                for (n, v), svc in sorted(services.items())}

    def stop_all(self, drain: bool = True) -> None:
        with self._lock:
            services = list(self._services.values())
            self._services.clear()
            self._latest.clear()
        for svc in services:
            svc.stop(drain=drain)

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.stop_all(drain=True)
