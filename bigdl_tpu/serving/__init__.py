"""bigdl_tpu.serving — TPU-native dynamic-batching inference engine.

The serving analog of the training stack's four perf PRs: where training
got K-step dispatch fusion and bucketed collectives, inference gets
request coalescing (one device dispatch serves many concurrent callers),
AOT-compiled power-of-two row buckets (steady-state traffic never
recompiles — the GL106 discipline applied to serving), bounded-queue
backpressure (``ServiceOverloaded``), graceful drain-then-stop shutdown,
and per-model stats (throughput, p50/p95/p99 latency, batch occupancy,
queue depth, dispatch count).

Reference lineage: BigDL 2.0 Cluster Serving (arXiv:2204.01715) and the
reference repo's ``PredictionService.scala`` — whose Python twin in
``optim/predictor.py`` is now a thin shim over this engine.

    from bigdl_tpu.serving import InferenceService
    svc = InferenceService(model, input_spec=((16,), np.float32))
    fut = svc.submit(x)            # Future; coalesced with other callers
    y = svc.predict(x)             # blocking sugar (chunks big inputs)
    svc.stats()                    # schema in README "serving"
    svc.stop()                     # drain then stop

    from bigdl_tpu.serving import ModelRegistry
    reg = ModelRegistry()
    reg.deploy("textclf", model, input_spec=..., quantize=True)
    reg.predict("textclf", x)      # newest version

Big-model + autoregressive serving (ROADMAP item 1's sharded half):

    from bigdl_tpu.serving import ShardedReplicaSet
    rs = ShardedReplicaSet(model, devices_per_replica=4)  # mesh slices

    from bigdl_tpu.serving import DecodeService
    dec = DecodeService(lm, slots=8, max_seq_len=256, eos_id=2)
    res = dec.generate([5, 17, 3], max_new_tokens=16)  # DecodeResult
"""

from bigdl_tpu.serving.batcher import (
    DeadlineExceeded, RequestBatcher, RequestSpecError, ServiceClosed,
    ServiceOverloaded,
)
from bigdl_tpu.serving.decode import DecodeResult, DecodeService
from bigdl_tpu.serving.metrics import LatencyReservoir, ServingMetrics
from bigdl_tpu.serving.registry import ModelRegistry
from bigdl_tpu.serving.service import (InferenceService, pad_rows,
                                       parse_row_buckets, row_buckets)
from bigdl_tpu.serving.sharded import ShardedReplicaSet

__all__ = [
    "InferenceService", "ModelRegistry", "RequestBatcher",
    "ServiceClosed", "ServiceOverloaded", "DeadlineExceeded",
    "RequestSpecError", "ServingMetrics", "LatencyReservoir",
    "row_buckets", "parse_row_buckets",
    "ShardedReplicaSet", "DecodeService", "DecodeResult",
]
