"""bigdl_tpu.frontend — the wire-level serving front end.

The network face of the serving plane (ROADMAP item 1, the Cluster-
Serving shape of BigDL 2.0, arXiv:2204.01715): a stdlib-only
HTTP/1.1 server over the existing :class:`~bigdl_tpu.serving.
ModelRegistry` / :class:`~bigdl_tpu.resilience.ReplicaSet` engines —
connections owned by a selectors-based event loop by default
(``frontend/eventloop.py`` + the ``frontend/http1.py`` incremental
parser, ROADMAP item 2; ``core="threaded"`` keeps the original
thread-per-connection core) — plus the three service-platform
behaviors large-scale serving treats as table stakes:

- :class:`FrontendServer` — ``POST /v1/models/<name>[:<v>]/predict``
  with JSON / raw-npy bodies, chunked ndjson streaming for multi-chunk
  predicts, ``X-Deadline-Ms`` propagated into the batcher's deadline
  path (504 on expiry), overloads as 429 + ``Retry-After``, trace ids
  minted/echoed so ``tools/obs_report.py`` stories span the wire hop;
- :class:`QosAdmission` / :class:`TenantSpec` — per-tenant admission:
  QoS classes (``latency`` | ``batch``) feeding the batcher's
  priority-preemption hook, token-bucket rate limits shed as 429, and
  ``serving/tenant=<t>/*`` metrics on the shared registry;
- :class:`HotCutover` — drain-free hot version cutover: warm → flip →
  drain wire connections → drain queue → undeploy (a deploy under load
  drops zero requests);
- :class:`ReplicaAutoscaler` — hysteresis + cooldown replica-count
  controller over the queue-depth/drain-EWMA load signal, actuating
  ``ReplicaSet.set_replica_count``.

Inertness contract (house discipline): importing this package — or
merely having it on the path — constructs nothing: no socket, no
thread, no config read.  Every component is explicit opt-in (gated in
``tests/test_frontend.py``).
"""

from bigdl_tpu.frontend.autoscale import ReplicaAutoscaler
from bigdl_tpu.frontend.cutover import CutoverDrainTimeout, HotCutover
from bigdl_tpu.frontend.qos import (BATCH, LATENCY, QosAdmission,
                                    TenantRateLimited, TenantSpec,
                                    TokenBucket, UnknownTenantError)
from bigdl_tpu.frontend.server import FrontendServer

__all__ = [
    "BATCH", "CutoverDrainTimeout", "FrontendServer", "HotCutover",
    "LATENCY", "QosAdmission", "ReplicaAutoscaler", "TenantRateLimited",
    "TenantSpec", "TokenBucket", "UnknownTenantError",
]
