"""EventLoopCore — the selectors-based non-blocking connection core.

ROADMAP item 2's tentpole: the PR-14 front end served every connection
on its own OS thread, a hard ceiling long before "heavy traffic from
millions of users".  Here one (or a few, with SO_REUSEPORT sharding)
loop threads own ALL sockets through a ``selectors`` readiness loop:
HTTP/1.1 is parsed incrementally (``frontend/http1.py``), requests run
through the SAME QoS-admission → resolve-and-pin → batcher submit path
as the threaded core, and responses — including chunked ndjson streams
— are written from future-completion callbacks with per-connection
write buffering and backpressure.  No thread per connection anywhere;
an idle connection costs one socket and ~1 KiB of parser state.

Threading model / lock contract (the GL2xx + lockdep story)
-----------------------------------------------------------
Single-owner discipline: every ``_Conn`` and ``_Exchange`` field is
touched ONLY from the one ``_Loop`` thread that accepted the
connection — no locks guard them, BY CONTRACT, because the only
cross-thread entry into a loop is :meth:`_Loop.call_soon`, whose ready
deque is the sole shared structure (guarded by its own lock).  Future
done-callbacks fire on batcher/ReplicaSet worker threads and therefore
never touch an exchange directly: they ``call_soon`` a bound method
and return.  Timers (``call_later``/``call_at``) are created and fired
on the loop thread only.  Everything shared across loops — the
connection ledger, the MetricRegistry, ``_WireInflight``, the QoS
gate — carries its own internal lock and is documented at its
definition site.

Semantic parity: the entire PR-14/15 wire surface (status taxonomy,
auth-before-body, streaming order + ``{"done":true}`` trailer, version
pinning, keep-alive desync guards, zero-drop cutover draining) is
mirrored method-for-method from ``server.py``'s threaded core; the
``tests/test_frontend.py`` gates run unchanged against this core.
"""

from __future__ import annotations

import heapq
import hmac
import json
import logging
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import List, Optional

from bigdl_tpu.frontend.http1 import (CHUNK_TRAILER, ProtocolError,
                                      RequestParser, encode_chunk,
                                      render_head)

logger = logging.getLogger("bigdl_tpu.frontend")

_READ_CHUNK = 64 * 1024
# write-buffer watermarks: a stream stops pumping results above HIGH
# and resumes below LOW, so one slow reader bounds its own memory
# instead of ballooning the loop's
_HIGH_WATER = 256 * 1024
_LOW_WATER = 64 * 1024
_ACCEPTS_PER_TICK = 64  # accept bursts can't starve established conns


class _Timer:
    """Cancelable loop-thread timer handle (heap entries are lazily
    skipped once cancelled)."""

    __slots__ = ("when", "fn", "cancelled")

    def __init__(self, when: float, fn):
        self.when = when
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class _Loop(threading.Thread):
    """One selector loop thread.  All registered sockets, timers and
    connection state are owned by this thread (single-owner — see the
    module docstring); ``call_soon`` is the only cross-thread entry."""

    def __init__(self, core: "EventLoopCore", idx: int):
        super().__init__(name=f"bigdl-tpu-frontend-loop{idx}",
                         daemon=True)
        self.core = core
        self.idx = idx
        self._sel = selectors.DefaultSelector()
        r, w = socket.socketpair()
        r.setblocking(False)
        w.setblocking(False)
        self._wake_r, self._wake_w = r, w
        self._lock = threading.Lock()
        self._ready = deque()   # guarded-by: _lock (sole cross-thread entry)
        self._woken = False     # guarded-by: _lock (coalesces wake bytes)
        self._timers: List = []  # loop-thread only (heap of (when, seq, _Timer))
        self._seq = 0            # loop-thread only
        self._stopping = False   # loop-thread only (set via call_soon)
        self.conns: set = set()  # loop-thread only
        self.listener: Optional[socket.socket] = None

    # -- cross-thread entry ------------------------------------------------
    def call_soon(self, fn, *args) -> None:
        """Schedule ``fn(*args)`` on the loop thread.  Safe from any
        thread (and from the loop thread itself)."""
        with self._lock:
            self._ready.append((fn, args))
            woken, self._woken = self._woken, True
        if not woken:
            try:
                self._wake_w.send(b"\0")
            except OSError:
                pass  # loop tearing down — nothing left to wake

    # -- loop-thread-only scheduling --------------------------------------
    def call_later(self, delay: float, fn) -> _Timer:
        return self.call_at(time.monotonic() + max(0.0, delay), fn)

    def call_at(self, when: float, fn) -> _Timer:
        t = _Timer(when, fn)
        self._seq += 1
        heapq.heappush(self._timers, (when, self._seq, t))
        return t

    # -- lifecycle ---------------------------------------------------------
    def add_listener(self, lsock: socket.socket) -> None:
        lsock.setblocking(False)
        self.listener = lsock

    def request_stop(self) -> None:
        self.call_soon(self._do_stop)

    def _do_stop(self) -> None:
        self._stopping = True

    def run(self) -> None:
        if self.core.pin_cpus:
            # pin this shard to one CPU (loop i → available cpu i mod
            # count): shards stop migrating across cores under load.
            # Silently inert where unsupported (macOS/Windows have no
            # sched_setaffinity) — the knob is best-effort by contract
            try:
                cpus = sorted(os.sched_getaffinity(0))
                if cpus:
                    os.sched_setaffinity(
                        0, {cpus[self.idx % len(cpus)]})
            except (AttributeError, OSError, ValueError):
                pass
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        if self.listener is not None:
            self._sel.register(self.listener, selectors.EVENT_READ,
                               self._on_accept_ready)
        if self.core.idle_timeout_s > 0:
            period = min(max(self.core.idle_timeout_s / 2.0, 0.05), 5.0)
            self.call_later(period, self._reap_tick)
        try:
            while True:
                now = time.monotonic()
                due = []
                while self._timers:
                    when, _seq, t = self._timers[0]
                    if t.cancelled:
                        heapq.heappop(self._timers)
                        continue
                    if when > now:
                        break
                    heapq.heappop(self._timers)
                    due.append(t)
                for t in due:
                    self._safe(t.fn)
                timeout = None
                if self._timers:
                    timeout = max(0.0, self._timers[0][0]
                                  - time.monotonic())
                with self._lock:
                    if self._ready:
                        timeout = 0.0
                for key, mask in self._sel.select(timeout):
                    if key.data is None:  # waker: drain the byte
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                        continue
                    self._safe(key.data, mask)
                with self._lock:
                    ready, self._ready = self._ready, deque()
                    self._woken = False
                for fn, args in ready:
                    self._safe(fn, *args)
                if self._stopping:
                    return
        finally:
            for conn in list(self.conns):
                conn.destroy_at_stop()
            self.conns.clear()
            if self.listener is not None:
                try:
                    self._sel.unregister(self.listener)
                except (KeyError, ValueError):
                    pass
                self.listener.close()
            try:
                self._sel.unregister(self._wake_r)
            except (KeyError, ValueError):
                pass
            self._wake_r.close()
            self._wake_w.close()
            self._sel.close()

    @staticmethod
    def _safe(fn, *args) -> None:
        """One callback must never kill the loop (it owns every other
        connection too)."""
        try:
            fn(*args)
        except BaseException:
            logger.exception("frontend loop callback failed")

    # -- accepting ---------------------------------------------------------
    def _on_accept_ready(self, _mask) -> None:
        for _ in range(_ACCEPTS_PER_TICK):
            try:
                sock, _addr = self.listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # listener closing under us (stop path)
            if not self.core.ledger.try_admit():
                # past the hard cap: the refusal is this close() — no
                # parser, no conn object, no thread, nothing to reap
                sock.close()
                continue
            target = self.core.pick_loop(self)
            if target is self:
                _Conn(self.core, self, sock)
            else:
                # single-listener fallback (no SO_REUSEPORT): hand the
                # socket to its owning loop — the conn is CONSTRUCTED
                # there, so single-owner discipline holds from byte 0
                target.call_soon(_Conn, self.core, target, sock)

    # -- idle reaping ------------------------------------------------------
    def _reap_tick(self) -> None:
        if self._stopping:
            return
        cutoff = time.monotonic() - self.core.idle_timeout_s
        for conn in list(self.conns):
            if conn.exchange is None and not conn.out_pending \
                    and conn.last_activity < cutoff:
                conn.close(reaped=True)
        period = min(max(self.core.idle_timeout_s / 2.0, 0.05), 5.0)
        self.call_later(period, self._reap_tick)


class _Conn:
    """One accepted connection.  Single-owner: every field is touched
    only on ``self.loop``'s thread (see module docstring — this is the
    loop-owned-state discipline graftlint's catalog documents)."""

    __slots__ = ("core", "loop", "sock", "parser", "exchange",
                 "head_checked", "peer_eof", "closing", "closed",
                 "last_activity", "_out", "_out_len", "_mask",
                 "_registered", "_pumping")

    def __init__(self, core: "EventLoopCore", loop: _Loop,
                 sock: socket.socket):
        self.core = core
        self.loop = loop
        self.sock = sock
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.parser = RequestParser()
        self.exchange = None          # active _Exchange, at most one
        self.head_checked = False     # early checks ran for current head
        self.peer_eof = False
        self.closing = False          # flush remaining output, then close
        self.closed = False
        self.last_activity = time.monotonic()
        self._out = deque()           # buffered response bytes
        self._out_len = 0
        self._mask = selectors.EVENT_READ
        self._registered = True
        self._pumping = False
        loop._sel.register(sock, self._mask, self._on_events)
        loop.conns.add(self)

    # -- readiness ---------------------------------------------------------
    def _on_events(self, mask) -> None:
        if mask & selectors.EVENT_WRITE:
            self._flush()
        if not self.closed and (mask & selectors.EVENT_READ):
            self._on_readable()

    def _set_interest(self, read: bool, write: bool) -> None:
        mask = (selectors.EVENT_READ if read else 0) \
            | (selectors.EVENT_WRITE if write else 0)
        if mask == self._mask or self.closed:
            return
        self._mask = mask
        if mask == 0:
            # zero interest (half-closed peer, nothing to write, an
            # exchange still computing): unregister entirely — a dead
            # read side left registered would wake every tick forever
            if self._registered:
                self.loop._sel.unregister(self.sock)
                self._registered = False
        elif not self._registered:
            self.loop._sel.register(self.sock, mask, self._on_events)
            self._registered = True
        else:
            self.loop._sel.modify(self.sock, mask, self._on_events)

    def _on_readable(self) -> None:
        try:
            data = self.sock.recv(_READ_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._abort()
            return
        if not data:
            # EOF ≠ gone: a half-closed client may still be reading
            # its response (the threaded core only learns of a real
            # disconnect from a failed WRITE — mirror that, but stop
            # polling a forever-readable dead read side)
            self.peer_eof = True
            self._set_interest(False, bool(self._out))
            if self.exchange is None and not self._out:
                self.close()
            return
        self.last_activity = time.monotonic()
        self.parser.feed(data)
        self.pump()

    # -- request framing → dispatch ---------------------------------------
    def pump(self) -> None:
        """Drive parsed requests into the core, one exchange at a time
        (no pipelining overlap: the next buffered request starts only
        after the current exchange finishes — same ordering the
        threaded core's sequential handler loop gives).  Re-entrant
        calls (an exchange that fails synchronously finishes inside
        ``dispatch``) flatten into the outer loop instead of
        recursing per buffered request."""
        if self._pumping:
            return
        self._pumping = True
        try:
            self._pump_inner()
        finally:
            self._pumping = False

    def _pump_inner(self) -> None:
        while not self.closed and not self.closing \
                and self.exchange is None:
            try:
                head = self.parser.head()
                if head is None:
                    return
                if not self.head_checked:
                    if not self.core.early_check(self, head):
                        return  # responded + closing
                    self.head_checked = True
                req = self.parser.poll()
                if req is None:
                    return
            except ProtocolError as e:
                self.core.protocol_error(self, e)
                return
            self.head_checked = False
            self.last_activity = time.monotonic()
            self.core.dispatch(self, req)

    def exchange_done(self, keep_alive: bool) -> None:
        self.exchange = None
        if self.closed:
            return
        self.last_activity = time.monotonic()
        if not keep_alive or self.peer_eof:
            self.close_when_flushed()
        else:
            self.pump()

    # -- writing -----------------------------------------------------------
    @property
    def out_pending(self) -> int:
        return self._out_len

    def write(self, data: bytes) -> None:
        if self.closed or not data:
            return
        self._out.append(memoryview(bytes(data)))
        self._out_len += len(data)
        self._flush()

    def _flush(self) -> None:
        while self._out:
            buf = self._out[0]
            try:
                n = self.sock.send(buf)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._abort()
                return
            self._out_len -= n
            if n == len(buf):
                self._out.popleft()
            else:
                self._out[0] = buf[n:]
                break
        if self._out:
            self._set_interest(not self.peer_eof, True)
            return
        self._set_interest(not self.peer_eof, False)
        if self.closing:
            self.close()
        elif self._out_len < _LOW_WATER and self.exchange is not None:
            self.exchange.on_drain()

    def close_when_flushed(self) -> None:
        if self._out:
            self.closing = True
        else:
            self.close()

    # -- teardown ----------------------------------------------------------
    def _abort(self) -> None:
        """Peer-driven failure (reset / failed send): tear down and let
        the active exchange classify it as a client disconnect."""
        ex = self.exchange
        self._teardown(reaped=False)
        if ex is not None:
            ex.on_client_gone()

    def close(self, reaped: bool = False) -> None:
        self._teardown(reaped=reaped)

    def destroy_at_stop(self) -> None:
        """Server-stop teardown: abandon the exchange quietly (no
        disconnect accounting — the peer did nothing wrong)."""
        ex = self.exchange
        if ex is not None:
            ex.abandon()
        self._teardown(reaped=False)

    def _teardown(self, reaped: bool) -> None:
        if self.closed:
            return
        self.closed = True
        self.exchange = None
        try:
            self.loop._sel.unregister(self.sock)
        except (KeyError, ValueError):
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.loop.conns.discard(self)
        self.core.ledger.release(reaped=reaped)


class EventLoopCore:
    """The loop-threaded connection core behind a
    :class:`~bigdl_tpu.frontend.server.FrontendServer` (selected by its
    ``core="eventloop"`` knob — the default).  Owns the listening
    socket(s) and loop threads; all HTTP semantics delegate to the
    server object so both cores share one behavior surface."""

    def __init__(self, server, *, host: str, port: int, shards: int = 1,
                 reuse_port: bool = False, idle_timeout_s: float = 0.0,
                 pin_cpus: bool = False):
        self.server = server
        self.host = host
        self.requested_port = int(port)
        self.shards = max(1, int(shards))
        self.reuse_port = bool(reuse_port)
        self.idle_timeout_s = float(idle_timeout_s)
        self.pin_cpus = bool(pin_cpus)
        self.ledger = server._conns
        self.loops: List[_Loop] = []
        self.port: Optional[int] = None
        self._fanout = False  # single listener feeding several loops
        self._rr = 0  # round-robin cursor (accepting-loop thread only)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        n = self.shards
        has_reuseport = hasattr(socket, "SO_REUSEPORT")
        want_reuseport = self.reuse_port or n > 1
        if n > 1 and not has_reuseport:
            logger.warning(
                "frontend: SO_REUSEPORT unavailable on this platform — "
                "falling back to one shared listener fanned out across "
                "%d loops", n)
        self.loops = [_Loop(self, i) for i in range(n)]
        listeners: List[socket.socket] = []
        try:
            first = self._bind(self.requested_port,
                               want_reuseport and has_reuseport)
            listeners.append(first)
            self.port = first.getsockname()[1]
            if n > 1 and has_reuseport:
                for _ in range(n - 1):
                    listeners.append(self._bind(self.port, True))
        except BaseException:
            for ls in listeners:
                ls.close()
            raise
        if len(listeners) == len(self.loops):
            for loop, ls in zip(self.loops, listeners):
                loop.add_listener(ls)
        else:
            self._fanout = True
            self.loops[0].add_listener(listeners[0])
        for loop in self.loops:
            loop.start()
        return self.port

    def _bind(self, port: int, reuseport: bool) -> socket.socket:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if reuseport:
                ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            ls.bind((self.host, port))
            # deep backlog: a C100K connect burst must queue in the
            # kernel (clamped to somaxconn), not SYN-drop into client
            # retransmit backoff
            ls.listen(4096)
        except BaseException:
            ls.close()
            raise
        return ls

    def stop(self) -> None:
        for loop in self.loops:
            loop.request_stop()
        for loop in self.loops:
            loop.join(timeout=2.0)
        self.loops = []

    @property
    def running(self) -> bool:
        return any(loop.is_alive() for loop in self.loops)

    def pick_loop(self, accepting: _Loop) -> _Loop:
        """Owning loop for a fresh connection.  With per-loop
        SO_REUSEPORT listeners the kernel already sharded — the
        accepting loop keeps it; the single-listener fallback
        round-robins (cursor touched only by the one accepting
        loop)."""
        if not self._fanout:
            return accepting
        self._rr = (self._rr + 1) % len(self.loops)
        return self.loops[self._rr]

    # -- shared HTTP semantics (mirrors the threaded handler) -------------
    def _auth_ok(self, head) -> bool:
        tok = self.server._auth_token
        if not tok:
            return True
        hdr = head.get("authorization", "")
        return hdr.startswith("Bearer ") and hmac.compare_digest(
            hdr[len("Bearer "):].strip(), tok)

    def early_check(self, conn: _Conn, head) -> bool:
        """Checks that must answer BEFORE the body is read (the
        401/404/411/413 keep-alive desync guards — all of them close).
        True → proceed to body framing; False → responded."""
        from bigdl_tpu.frontend.server import (_GENERATE_RE, _MAX_BODY,
                                               _PREDICT_RE)
        if not self._auth_ok(head):
            self.respond(conn, 401,
                         {"error": "missing or invalid bearer token"},
                         {"WWW-Authenticate": "Bearer"}, close=True)
            return False
        if head.method == "GET":
            return True
        if head.method != "POST":
            self.respond(conn, 501,
                         {"error": f"unsupported method "
                                   f"{head.method!r}"}, close=True)
            return False
        if _PREDICT_RE.match(head.target) is None \
                and _GENERATE_RE.match(head.target) is None:
            self.respond(conn, 404,
                         {"error": f"no route {head.target}"},
                         close=True)
            return False
        if head.get("transfer-encoding"):
            # chunked framing: the parser's embedded ChunkedDecoder
            # enforces the whole 400/413/501 taxonomy itself (incl.
            # the TE+CL smuggling refusal), so no length check here
            return True
        cl = head.get("content-length")
        try:
            length = int(cl) if cl is not None else -1
        except ValueError:
            self.respond(conn, 400, {"error": "unreadable "
                                              "Content-Length"},
                         close=True)
            return False
        if length < 0:
            self.respond(conn, 411, {"error": "Content-Length "
                                              "required"}, close=True)
            return False
        if length > _MAX_BODY:
            self.respond(conn, 413,
                         {"error": f"body of {length} bytes exceeds "
                                   f"the {_MAX_BODY} byte cap"},
                         close=True)
            return False
        return True

    def protocol_error(self, conn: _Conn, e: ProtocolError) -> None:
        self.respond(conn, e.status, {"error": str(e)}, close=True)

    def respond(self, conn: _Conn, status: int, obj, headers=None,
                *, close: bool = False, keep_alive: bool = True) -> None:
        """One complete JSON response (counted — same accounting point
        as the threaded handler's ``send_json``)."""
        self.server._count_status(status)
        body = json.dumps(obj).encode("utf-8")
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        must_close = close or not keep_alive
        conn.write(render_head(status, hdrs, content_length=len(body),
                               close=must_close) + body)
        if must_close:
            conn.close_when_flushed()

    def dispatch(self, conn: _Conn, req) -> None:
        from bigdl_tpu.frontend.server import _GENERATE_RE
        if req.method == "GET":
            if req.target == "/v1/models":
                self.respond(conn, 200, {"models": self.server.models()},
                             keep_alive=req.keep_alive)
            else:
                self.respond(conn, 404, {
                    "error": f"no route {req.target}",
                    "routes": ["/v1/models",
                               "POST /v1/models/<name>[:<v>]"
                               "/predict",
                               "POST /v1/models/<name>[:<v>]"
                               "/generate"]}, keep_alive=req.keep_alive)
            return
        if _GENERATE_RE.match(req.target) is not None:
            _GenExchange(self, conn, req).start()
            return
        _Exchange(self, conn, req).start()


class _Exchange:
    """One POST .../predict exchange as a loop-owned state machine —
    the async mirror of the threaded core's ``_run_predict`` /
    ``_respond_stream`` (single-owner: all fields loop-thread only;
    future callbacks re-enter via ``loop.call_soon``)."""

    def __init__(self, core: EventLoopCore, conn: _Conn, req):
        from bigdl_tpu.frontend.server import _PREDICT_RE
        self.core = core
        self.server = core.server
        self.conn = conn
        self.loop = conn.loop
        self.req = req
        m = _PREDICT_RE.match(req.target)
        self.name = m.group("name")
        self.req_version = (int(m.group("version"))
                            if m.group("version") else None)
        self.ctype = (req.get("content-type") or "") \
            .split(";")[0].strip().lower()
        self.accept = (req.get("accept") or "") \
            .split(",")[0].strip().lower()
        self.tenant = req.get("x-tenant")
        self.trace_id = req.get("x-trace-id")
        self._settled = False
        self._entered = False   # past body parse → qos/latency recorded
        self._t0 = 0.0
        self._span_t0: Optional[int] = None
        self._key = None
        self._pinned = False
        self._backend = None
        self._brk = None
        self._attempt = 0
        self.deadline: Optional[float] = None
        self.ctx = None
        self.x = None
        self.rows = 0
        self._fut = None
        self._deadline_timer: Optional[_Timer] = None
        self._retry_timer: Optional[_Timer] = None
        # stream state
        self._max_batch = 0
        self._next_off = 0
        self._inflight: List = []  # [(offset, n, future)], oldest first
        self._sent = 0
        self._stalls = 0
        self._started = False
        self._paused = False

    # -- entry -------------------------------------------------------------
    def start(self) -> None:
        server = self.server
        raw_deadline = self.req.get("x-deadline-ms")
        deadline_ms = None
        if raw_deadline is not None:
            try:
                deadline_ms = float(raw_deadline)
            except ValueError:
                # pre-dispatch reject (mirrors do_POST: no requests
                # count, no trace span — the exchange never began)
                self.core.respond(self.conn, 400,
                                  {"error": f"bad X-Deadline-Ms "
                                            f"{raw_deadline!r}"},
                                  keep_alive=self.req.keep_alive)
                return
        tracer = server.tracer
        if tracer is not None and tracer.enabled:
            if self.trace_id is None:
                # mint HERE so the wire_request span carries the id
                # (same reasoning as the threaded _traced_predict)
                from bigdl_tpu.telemetry.context import new_trace_id
                self.trace_id = new_trace_id()
            self._span_t0 = time.perf_counter_ns()
        self.conn.exchange = self
        self._t0 = time.monotonic()
        server.metrics.counter("frontend/requests").inc()
        try:
            server.qos.admit(self.tenant)
            self.deadline = (self._t0 + deadline_ms / 1e3
                             if deadline_ms is not None else None)
            from bigdl_tpu.telemetry.context import RequestContext
            self.ctx = RequestContext(trace_id=self.trace_id,
                                      tenant=self.tenant,
                                      deadline=self.deadline)
            server._resolve(self.name, self.req_version)  # 404 precedence
            self.x, self.rows = server._parse_body(self.req.body,
                                                   self.ctype)
        except BaseException as e:
            self._finish_error(e)
            return
        self._entered = True
        self._begin_attempt()

    # -- resolve-and-pin attempts (the ServiceClosed cutover retry) --------
    def _begin_attempt(self) -> None:
        server = self.server
        try:
            key, backend, brk = server._resolve_pinned(self.name,
                                                       self.req_version)
        except BaseException as e:
            self._finish_error(e)
            return
        self._key, self._backend, self._brk = key, backend, brk
        self._pinned = True
        try:
            max_batch = server._backend_max_batch(backend)
            if self.rows <= max_batch:
                fut = server._submit(backend, self.x, self.deadline,
                                     self.ctx)
            else:
                self._stream_init(max_batch)
                return
        except BaseException as e:
            self._attempt_failed(e)
            return
        self._fut = fut
        if self.deadline is not None:
            self._deadline_timer = self.loop.call_at(
                self.deadline, self._on_single_deadline)
        fut.add_done_callback(
            lambda f: self.loop.call_soon(self._single_done, f))

    def _attempt_failed(self, e: BaseException) -> None:
        """A pinned attempt died before anything was served: unpin and
        either retry onto the cutover successor (idempotent — nothing
        left this server) or answer with the real status."""
        from bigdl_tpu.serving.batcher import ServiceClosed
        self._unpin()
        self._cancel_timers()
        if isinstance(e, ServiceClosed) and self.req_version is None \
                and self._attempt < 2:
            self._attempt += 1
            self._begin_attempt()
            return
        self._finish_error(e)

    def _unpin(self) -> None:
        if self._pinned:
            self._pinned = False
            self.server.inflight.exit(self._key)  # releases: wire_inflight

    # -- single-response path ---------------------------------------------
    def _single_done(self, fut) -> None:
        if self._settled:
            return
        from bigdl_tpu.serving.registry import ModelRegistry
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        try:
            out = self.server._result_or_504(fut, 0)  # done: no block
        except BaseException as e:
            if not fut.cancelled():
                ModelRegistry.record_outcome(self._brk, e)
            self._attempt_failed(e)
            return
        ModelRegistry.record_outcome(self._brk, None)
        self._respond_single(out)

    def _on_single_deadline(self) -> None:
        if self._settled:
            return
        from bigdl_tpu.serving.batcher import DeadlineExceeded
        from bigdl_tpu.serving.registry import ModelRegistry
        fut = self._fut
        fut.cancel()  # refuse late service; batcher honors cancel
        e = DeadlineExceeded("wire deadline expired while the request "
                             "was queued")
        if not fut.cancelled():
            ModelRegistry.record_outcome(self._brk, e)
        self._attempt_failed(e)

    def _respond_single(self, out) -> None:
        import numpy as np
        from bigdl_tpu.frontend.server import _NPY, _jsonify
        server = self.server
        name, version = self._key
        headers = {"X-Trace-Id": self.ctx.trace_id,
                   "X-Model-Version": str(version)}
        if self.accept == _NPY and isinstance(out, np.ndarray):
            from io import BytesIO
            buf = BytesIO()
            np.save(buf, out, allow_pickle=False)
            payload = buf.getvalue()
            headers["Content-Type"] = _NPY
            server._count_status(200)
            self.conn.write(render_head(200, headers,
                                        content_length=len(payload))
                            + payload)
        else:
            body = json.dumps({
                "model": name, "version": version,
                "trace_id": self.ctx.trace_id,
                "outputs": _jsonify(out)}).encode("utf-8")
            headers["Content-Type"] = "application/json"
            server._count_status(200)
            self.conn.write(render_head(200, headers,
                                        content_length=len(body))
                            + body)
        self._finish(200, ok=True)

    # -- streaming path ----------------------------------------------------
    def _stream_init(self, max_batch: int) -> None:
        # (re)entered per pinned attempt — a ServiceClosed retry onto
        # the cutover successor restarts the whole stream (nothing was
        # committed: retries only happen before the first result)
        self._max_batch = max_batch
        self._next_off = 0
        self._sent = 0
        self._stalls = 0
        self._paused = False
        if self.deadline is not None:
            self._deadline_timer = self.loop.call_at(
                self.deadline, self._on_stream_deadline)
        self._stream_tick()

    def _leaf_slice(self, lo: int, hi: int):
        if isinstance(self.x, dict):
            return {k: v[lo:hi] for k, v in self.x.items()}
        return self.x[lo:hi]

    def _stream_tick(self) -> None:
        """The pump: flush completed head-of-line results, submit up
        to the window, finish with the done trailer.  Re-entered from
        chunk-future completion, the overload retry timer, and
        write-buffer drain."""
        from bigdl_tpu.serving.batcher import ServiceOverloaded
        if self._settled:
            return
        server = self.server
        while True:
            while self._inflight and self._inflight[0][2].done():
                if not self._flush_head():
                    return  # stream failed/settled inside
            if self.conn.out_pending > _HIGH_WATER:
                self._paused = True  # resumed by on_drain
                return
            if self._next_off < self.rows \
                    and len(self._inflight) < server._stream_window:
                off = self._next_off
                hi = min(off + self._max_batch, self.rows)
                try:
                    fut = server._submit(self._backend,
                                         self._leaf_slice(off, hi),
                                         self.deadline, self.ctx)
                except ServiceOverloaded as e:
                    if self._inflight:
                        # oldest chunk's completion re-ticks and the
                        # submit retries — the flush-oldest rule,
                        # without parking a thread
                        return
                    # foreign traffic owns the queue: honor the drain
                    # hint briefly, but give up eventually on a
                    # deadline-less stream rather than retrying forever
                    self._stalls += 1
                    if self.deadline is None and self._stalls > 200:
                        self._stream_fail(e)
                        return
                    self._retry_timer = self.loop.call_later(
                        min(0.05, (e.retry_after_ms or 10.0) / 1e3),
                        self._stream_tick)
                    return
                except BaseException as e:
                    self._stream_fail(e)
                    return
                self._stalls = 0
                self._next_off = hi
                self._inflight.append((off, hi - off, fut))
                fut.add_done_callback(
                    lambda f: self.loop.call_soon(self._stream_tick))
                continue
            if self._next_off >= self.rows and not self._inflight:
                self._stream_done()
                return
            return  # waiting on in-flight futures

    def _flush_head(self) -> bool:
        """Resolve the OLDEST in-flight chunk and stream its line (the
        200 chunked header is committed here, by the FIRST result)."""
        from bigdl_tpu.frontend.server import _jsonify
        from bigdl_tpu.serving.registry import ModelRegistry
        off, n, fut = self._inflight.pop(0)
        try:
            # done already, so this never blocks the loop; the shared
            # helper keeps the resolved-timeout normalization identical
            # to the threaded core's flush
            out = self.server._result_or_504(fut, 0)
        except BaseException as e:
            if not fut.cancelled():
                ModelRegistry.record_outcome(self._brk, e)
            self._stream_fail(e)
            return False
        ModelRegistry.record_outcome(self._brk, None)
        try:
            self._ensure_started()
            self.conn.write(encode_chunk(json.dumps(
                {"offset": off, "rows": n,
                 "outputs": _jsonify(out)}).encode("utf-8") + b"\n"))
        except BaseException as e:
            # e.g. an unserializable output pytree — an internal fault
            # AFTER the result resolved (the threaded core catches the
            # same family in _respond_stream's failure tail)
            self._stream_fail(e)
            return False
        self.server.metrics.counter("frontend/stream_chunks").inc()
        self._sent += n
        return True

    def _ensure_started(self) -> None:
        if self._started:
            return
        from bigdl_tpu.frontend.server import _NDJSON
        self._started = True
        self.conn.write(render_head(
            200, {"Content-Type": _NDJSON,
                  "X-Trace-Id": self.ctx.trace_id,
                  "X-Model-Version": str(self._key[1])}, chunked=True))

    def _on_stream_deadline(self) -> None:
        if self._settled:
            return
        from bigdl_tpu.serving.batcher import DeadlineExceeded
        self._stream_fail(DeadlineExceeded(
            f"deadline passed after {self._sent} of {self.rows} rows "
            f"streamed"))

    def _stream_fail(self, e: BaseException) -> None:
        """Mirror of the threaded ``_respond_stream`` failure tail:
        cancel the backlog FIRST, answer with the real status if the
        200 was never committed (incl. the cutover ServiceClosed
        retry), else an error line; a client disconnect is the
        client's outcome, never a 5xx."""
        from bigdl_tpu.frontend.server import _HTTPError
        if self._settled:
            return
        for _off, _n, fut in self._inflight:
            fut.cancel()
        self._inflight = []
        if not self._started:
            self._attempt_failed(e)
            return
        if isinstance(e, ConnectionError):
            self.server.metrics.counter(
                "frontend/client_disconnects").inc()
            self._finish(200, ok=False)
            return
        status, body, _hdrs = self.server._classify(e)
        if status >= 500 and status != 504 \
                and not isinstance(e, _HTTPError):
            logger.error("frontend mid-stream 5xx after %d rows",
                         self._sent, exc_info=e)
        self.server._count_status(status)
        self.conn.write(encode_chunk(json.dumps(
            {"error": body["error"], "status": status,
             "rows_streamed": self._sent}).encode("utf-8") + b"\n"))
        self.conn.write(CHUNK_TRAILER)
        self._finish(200, ok=False)

    def _stream_done(self) -> None:
        self._ensure_started()
        self.conn.write(encode_chunk(json.dumps(
            {"done": True, "rows": self._sent,
             "trace_id": self.ctx.trace_id}).encode("utf-8") + b"\n"))
        self.conn.write(CHUNK_TRAILER)
        self.server._count_status(200)
        self._finish(200, ok=True)

    # -- conn-driven notifications ----------------------------------------
    def on_drain(self) -> None:
        if self._paused and not self._settled:
            self._paused = False
            self._stream_tick()

    def on_client_gone(self) -> None:
        """The conn died under us (reset / failed send).  A committed
        stream aborts as a client disconnect; a single in-flight
        predict completes normally — its response is simply dropped
        (the threaded core likewise only fails at write time)."""
        if self._settled:
            return
        if self._started:
            self._stream_fail(ConnectionError(
                "client disconnected mid-stream"))
        # not started (single predict, or stream before its first
        # result): let the exchange complete — its writes are dropped
        # by the closed conn, exactly where the threaded core's write
        # would have failed silently

    def abandon(self) -> None:
        """Server-stop teardown: drop everything without response or
        accounting (the process is taking the whole plane down)."""
        if self._settled:
            return
        self._settled = True
        self._cancel_timers()
        for _off, _n, fut in self._inflight:
            fut.cancel()
        self._inflight = []
        if self._fut is not None:
            self._fut.cancel()
        self._unpin()

    # -- error + completion tails -----------------------------------------
    def _finish_error(self, e: BaseException) -> None:
        from bigdl_tpu.frontend.server import _HTTPError
        status, body, hdrs = self.server._classify(e)
        if status >= 500 and status != 504 \
                and not isinstance(e, _HTTPError):
            logger.error("frontend 5xx on %s", self.req.target,
                         exc_info=e)
        self.core.respond(self.conn, status, body, hdrs)
        self._finish(status, ok=False)

    def _cancel_timers(self) -> None:
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
            self._deadline_timer = None
        if self._retry_timer is not None:
            self._retry_timer.cancel()
            self._retry_timer = None

    def _finish(self, trace_status: int, *, ok: bool) -> None:
        if self._settled:
            return
        self._settled = True
        self._cancel_timers()
        self._unpin()
        server = self.server
        if self._entered:
            dt = time.monotonic() - self._t0
            server.qos.record_result(self.tenant, dt, ok)
            server._latency_h.observe(dt)
        if self._span_t0 is not None:
            tracer = server.tracer
            tracer.record("wire_request", self._span_t0,
                          time.perf_counter_ns(), cat="serving",
                          model=self.name, tenant=self.tenant,
                          trace_id=self.trace_id)
            if trace_status != 200:
                tracer.instant("wire_error", cat="serving",
                               model=self.name, tenant=self.tenant,
                               status=trace_status)
        self.conn.exchange_done(self.req.keep_alive)


class _GenExchange:
    """One POST .../generate exchange — the loop-owned token-streaming
    twin of the threaded core's ``_run_generate``/``_respond_generate``.
    Single-owner like :class:`_Exchange`: every field is loop-thread
    only.  The decode scheduler thread crosses in at exactly two
    points — the ``on_token`` callback and the future's done callback —
    and both only ``call_soon`` a bound method; because one scheduler
    thread emits every token BEFORE settling the future, the ready
    deque preserves token order and the done entry lands after the last
    token."""

    def __init__(self, core: EventLoopCore, conn: _Conn, req):
        from bigdl_tpu.frontend.server import _GENERATE_RE
        self.core = core
        self.server = core.server
        self.conn = conn
        self.loop = conn.loop
        self.req = req
        m = _GENERATE_RE.match(req.target)
        self.name = m.group("name")
        self.req_version = (int(m.group("version"))
                            if m.group("version") else None)
        self.ctype = (req.get("content-type") or "") \
            .split(";")[0].strip().lower()
        self.tenant = req.get("x-tenant")
        self.trace_id = req.get("x-trace-id")
        self._settled = False
        self._entered = False
        self._t0 = 0.0
        self._span_t0: Optional[int] = None
        self._key = None
        self._pinned = False
        self._backend = None
        self._brk = None
        self._attempt = 0
        self.deadline: Optional[float] = None
        self.ctx = None
        self.prompt = None
        self.max_new = None
        self._fut = None
        self._deadline_timer: Optional[_Timer] = None
        self._started = False
        self._sent = 0

    # -- entry -------------------------------------------------------------
    def start(self) -> None:
        server = self.server
        raw_deadline = self.req.get("x-deadline-ms")
        deadline_ms = None
        if raw_deadline is not None:
            try:
                deadline_ms = float(raw_deadline)
            except ValueError:
                self.core.respond(self.conn, 400,
                                  {"error": f"bad X-Deadline-Ms "
                                            f"{raw_deadline!r}"},
                                  keep_alive=self.req.keep_alive)
                return
        tracer = server.tracer
        if tracer is not None and tracer.enabled:
            if self.trace_id is None:
                from bigdl_tpu.telemetry.context import new_trace_id
                self.trace_id = new_trace_id()
            self._span_t0 = time.perf_counter_ns()
        self.conn.exchange = self
        self._t0 = time.monotonic()
        server.metrics.counter("frontend/requests").inc()
        try:
            server.qos.admit(self.tenant)
            self.deadline = (self._t0 + deadline_ms / 1e3
                             if deadline_ms is not None else None)
            from bigdl_tpu.telemetry.context import RequestContext
            self.ctx = RequestContext(trace_id=self.trace_id,
                                      tenant=self.tenant,
                                      deadline=self.deadline)
            server._resolve(self.name, self.req_version)  # 404 first
            self.prompt, self.max_new = server._parse_generate_body(
                self.req.body, self.ctype)
        except BaseException as e:
            self._finish_error(e)
            return
        self._entered = True
        self._begin_attempt()

    # -- resolve-and-pin (the ServiceClosed cutover retry) -----------------
    def _begin_attempt(self) -> None:
        from bigdl_tpu.frontend.server import _HTTPError
        from bigdl_tpu.serving.batcher import RequestSpecError
        server = self.server
        try:
            key, backend, brk = server._resolve_pinned(self.name,
                                                       self.req_version)
        except BaseException as e:
            self._finish_error(e)
            return
        self._key, self._backend, self._brk = key, backend, brk
        self._pinned = True
        if not getattr(backend, "is_decode_backend", False):
            self._fail(_HTTPError(
                400, f"model {self.name!r} is not a decode backend — "
                     f"use /predict"))
            return
        try:
            fut = backend.submit(self.prompt,
                                 max_new_tokens=self.max_new,
                                 deadline=self.deadline, ctx=self.ctx,
                                 on_token=self._on_token_threadsafe)
        except RequestSpecError as e:
            self._fail(_HTTPError(400, str(e)))
            return
        except BaseException as e:
            self._fail(e)
            return
        self._fut = fut
        if self.deadline is not None:
            self._deadline_timer = self.loop.call_at(
                self.deadline, self._on_deadline)
        fut.add_done_callback(
            lambda f: self.loop.call_soon(self._done, f))

    # -- token stream ------------------------------------------------------
    def _on_token_threadsafe(self, index: int, token: int) -> None:
        """Runs on the decode scheduler thread — the ONE rule is it
        only crosses via call_soon (single-owner discipline)."""
        self.loop.call_soon(self._on_token, int(index), int(token))

    def _on_token(self, index: int, token: int) -> None:
        if self._settled or self.conn.closed:
            return
        self._ensure_started()
        self.conn.write(encode_chunk(json.dumps(
            {"index": index, "token": token}).encode("utf-8") + b"\n"))
        self._sent += 1

    def _ensure_started(self) -> None:
        if self._started:
            return
        from bigdl_tpu.frontend.server import _NDJSON
        self._started = True
        self.conn.write(render_head(
            200, {"Content-Type": _NDJSON,
                  "X-Trace-Id": self.ctx.trace_id,
                  "X-Model-Version": str(self._key[1])}, chunked=True))

    # -- completion --------------------------------------------------------
    def _done(self, fut) -> None:
        if self._settled:
            return
        from bigdl_tpu.serving.registry import ModelRegistry
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
            self._deadline_timer = None
        try:
            out = self.server._result_or_504(fut, 0)  # done: no block
        except BaseException as e:
            if not fut.cancelled():
                ModelRegistry.record_outcome(self._brk, e)
            self._fail(e)
            return
        ModelRegistry.record_outcome(self._brk, None)
        self._ensure_started()
        self.conn.write(encode_chunk(json.dumps(
            {"done": True,
             "tokens": [int(t) for t in out.tokens],
             "n": len(out.tokens),
             "finish_reason": out.finish_reason,
             "trace_id": self.ctx.trace_id}).encode("utf-8") + b"\n"))
        self.conn.write(CHUNK_TRAILER)
        self.server._count_status(200)
        self.server.metrics.counter(
            "frontend/generate_tokens").inc(self._sent)
        self._finish(200, ok=True)

    def _on_deadline(self) -> None:
        if self._settled:
            return
        fut = self._fut
        if fut is not None and fut.cancel():
            # still queued past the wire deadline: refuse late service
            # (a RUNNING sequence is failed by the scheduler's own
            # per-step deadline check, which settles the future)
            from bigdl_tpu.serving.batcher import DeadlineExceeded
            self._fail(DeadlineExceeded(
                "wire deadline expired while the prompt was queued"))

    # -- failure tails -----------------------------------------------------
    def _fail(self, e: BaseException) -> None:
        """Real status if the 200 was never committed (incl. the
        cutover ServiceClosed retry), else an error line + trailer —
        the threaded ``_respond_generate`` failure tail, loop-shaped."""
        from bigdl_tpu.frontend.server import _HTTPError
        from bigdl_tpu.serving.batcher import ServiceClosed
        if self._settled:
            return
        if self._fut is not None:
            self._fut.cancel()
        if not self._started:
            self._unpin()
            self._cancel_timers()
            if isinstance(e, ServiceClosed) \
                    and self.req_version is None and self._attempt < 2:
                self._attempt += 1
                self._fut = None
                self._begin_attempt()
                return
            self._finish_error(e)
            return
        if isinstance(e, ConnectionError):
            self.server.metrics.counter(
                "frontend/client_disconnects").inc()
            self._finish(200, ok=False)
            return
        status, body, _hdrs = self.server._classify(e)
        if status >= 500 and status != 504 \
                and not isinstance(e, _HTTPError):
            logger.error("frontend mid-generate 5xx after %d tokens",
                         self._sent, exc_info=e)
        self.server._count_status(status)
        self.conn.write(encode_chunk(json.dumps(
            {"error": body["error"], "status": status,
             "tokens_streamed": self._sent}).encode("utf-8") + b"\n"))
        self.conn.write(CHUNK_TRAILER)
        self._finish(200, ok=False)

    def _finish_error(self, e: BaseException) -> None:
        from bigdl_tpu.frontend.server import _HTTPError
        status, body, hdrs = self.server._classify(e)
        if status >= 500 and status != 504 \
                and not isinstance(e, _HTTPError):
            logger.error("frontend 5xx on %s", self.req.target,
                         exc_info=e)
        self.core.respond(self.conn, status, body, hdrs)
        self._finish(status, ok=False)

    # -- conn-driven notifications ----------------------------------------
    def on_drain(self) -> None:
        pass  # token lines are tiny; no pull-driven pump to resume

    def on_client_gone(self) -> None:
        if self._settled:
            return
        if self._started:
            self._fail(ConnectionError(
                "client disconnected mid-generate"))
        # not started: let the exchange complete — writes are dropped
        # by the closed conn (same contract as _Exchange)

    def abandon(self) -> None:
        if self._settled:
            return
        self._settled = True
        self._cancel_timers()
        if self._fut is not None:
            self._fut.cancel()
        self._unpin()

    # -- bookkeeping -------------------------------------------------------
    def _unpin(self) -> None:
        if self._pinned:
            self._pinned = False
            self.server.inflight.exit(self._key)  # releases: wire_inflight

    def _cancel_timers(self) -> None:
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
            self._deadline_timer = None

    def _finish(self, trace_status: int, *, ok: bool) -> None:
        if self._settled:
            return
        self._settled = True
        self._cancel_timers()
        self._unpin()
        server = self.server
        if self._entered:
            dt = time.monotonic() - self._t0
            server.qos.record_result(self.tenant, dt, ok)
            server._latency_h.observe(dt)
        if self._span_t0 is not None:
            tracer = server.tracer
            tracer.record("wire_request", self._span_t0,
                          time.perf_counter_ns(), cat="serving",
                          model=self.name, tenant=self.tenant,
                          trace_id=self.trace_id)
            if trace_status != 200:
                tracer.instant("wire_error", cat="serving",
                               model=self.name, tenant=self.tenant,
                               status=trace_status)
        self.conn.exchange_done(self.req.keep_alive)
