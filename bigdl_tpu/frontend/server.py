"""FrontendServer — the wire-level serving front end (HTTP/1.1).

ROADMAP item 1's headline gap: everything below this module is
in-process (PR 5 coalescing engine, PR 10 self-healing ``ReplicaSet``,
PR 11 admin beachhead) — nothing could reach it over a wire.  This is
the Cluster-Serving shape of the lineage paper (BigDL 2.0,
arXiv:2204.01715 §3: a network front end turns the library into a
service), built with the same stdlib-only discipline as
``telemetry/admin.py`` (threaded ``http.server``, no grpc/flask):

- ``POST /v1/models/<name>[:<version>]/predict`` — JSON bodies
  (``{"inputs": <nested lists | {leaf: nested lists}>}``) or raw
  ``.npy`` bytes (``Content-Type: application/x-npy``) for bulk.  The
  response echoes the trace id and returns ``outputs`` as nested
  lists; with ``Accept: application/x-npy`` a single-array output
  comes back as raw npy bytes.
- **Chunked streaming for multi-chunk predicts**: inputs larger than
  the backend's ``max_batch_size`` stream back as
  ``application/x-ndjson`` over HTTP chunked transfer encoding — one
  JSON line per coalescible chunk as it completes (bounded in-flight
  submission window, results in input order), closed by a
  ``{"done": true}`` trailer line.  The resolved backend/version is
  PINNED for the whole exchange, so a hot cutover never splits one
  streaming request across versions.
- **Backpressure maps to HTTP**: a queue overload or a tenant
  rate-limit shed (:class:`~bigdl_tpu.frontend.qos.TenantRateLimited`)
  returns 429 with ``Retry-After`` (seconds, ceiling) and
  ``X-Retry-After-Ms`` (exact) from ``ServiceOverloaded.
  retry_after_ms``; a missed deadline returns 504; an unknown model
  404; a malformed request 400; strict-mode undeclared (or missing)
  tenants 403.
- **Deadlines ride a header**: ``X-Deadline-Ms: 250`` becomes the
  monotonic deadline propagated into the existing
  ``serving/batcher._Request.deadline`` path — expired work is refused
  before the device call, exactly like in-process submits.
- **Trace ids span the wire hop**: ``X-Trace-Id`` (or a freshly minted
  id) seeds the :class:`~bigdl_tpu.telemetry.RequestContext` the
  request travels with, is echoed back in the response, and — when a
  tracer is attached — the whole exchange lands as a ``wire_request``
  span carrying tenant/model/status, so ``tools/obs_report.py``
  stories start at the socket.

Inertness contract (house discipline): nothing in this package runs
unless a ``FrontendServer`` is explicitly constructed — no socket, no
thread, no import-time side effects (the zero-extra-threads gate in
``tests/test_frontend.py``).  Everything here is host-side: no jax
import; inputs/outputs are numpy pytrees.

Security posture: binds ``127.0.0.1`` only by default, where the
historical no-auth behavior is unchanged.  A NON-loopback bind is
refused unless a bearer token is configured
(``Config.frontend_auth_token`` / ``BIGDL_TPU_FRONTEND_AUTH_TOKEN`` or
the ``auth_token=`` constructor arg); with a token configured, every
request must carry ``Authorization: Bearer <token>`` (constant-time
compared) or is refused 401 before the body is read.  ``X-Tenant``
stays a declared QoS tag, never a credential — the ROADMAP item-1
wire-auth gap, closed.
"""

from __future__ import annotations

import hmac
import json
import logging
import re
import threading
import time
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from io import BytesIO
from typing import Dict, Optional, Tuple

import numpy as np

from bigdl_tpu.frontend.qos import (QosAdmission, TenantRateLimited,
                                    UnknownTenantError)
from concurrent.futures import TimeoutError as FutureTimeoutError

from bigdl_tpu.serving.batcher import (DeadlineExceeded,
                                       RequestSpecError, ServiceClosed,
                                       ServiceOverloaded)
from bigdl_tpu.telemetry.context import RequestContext
from bigdl_tpu.telemetry.registry import MetricRegistry

logger = logging.getLogger("bigdl_tpu.frontend")

_PREDICT_RE = re.compile(
    r"^/v1/models/(?P<name>[^/:]+)(?::(?P<version>\d+))?/predict$")
_GENERATE_RE = re.compile(
    r"^/v1/models/(?P<name>[^/:]+)(?::(?P<version>\d+))?/generate$")
_NPY = "application/x-npy"
_NDJSON = "application/x-ndjson"
_MAX_BODY = 256 << 20  # refuse absurd Content-Length up front


class _WireInflight:
    """Per-(model, version) count of wire requests currently being
    served — the thing hot cutover drains.  A streaming predict counts
    as ONE wire request for its whole exchange (it pinned the
    version)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._counts: Dict[Tuple[str, int], int] = {}  # guarded-by: _cond

    def enter(self, key: Tuple[str, int]) -> None:  # acquires: wire_inflight
        with self._cond:
            self._counts[key] = self._counts.get(key, 0) + 1  # acquires: wire_inflight

    def exit(self, key: Tuple[str, int]) -> None:  # releases: wire_inflight
        with self._cond:
            n = self._counts.get(key, 0) - 1
            if n <= 0:
                self._counts.pop(key, None)  # releases: wire_inflight
            else:
                self._counts[key] = n  # releases: wire_inflight
            self._cond.notify_all()

    def count(self, key: Tuple[str, int]) -> int:
        with self._cond:
            return self._counts.get(key, 0)

    def wait_idle(self, key: Tuple[str, int],
                  timeout: Optional[float]) -> bool:
        """Block until no wire request holds ``key`` (True) or the
        timeout passes with some still in flight (False)."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._cond:
            while self._counts.get(key, 0) > 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining
                                if remaining is not None else 1.0)
            return True


class _ConnLedger:
    """Open-connection accounting + the hard cap, shared by BOTH
    connection cores (threaded and event-loop): ``try_admit`` is the
    one cheap gate every fresh accept passes, ``release`` the one exit.
    Mirrors into the server's MetricRegistry (``frontend/
    open_connections`` gauge + accepted/closed/reaped/refused
    counters) so a zero-traffic scrape already shows the schema."""

    def __init__(self, metrics: MetricRegistry, max_connections: int):
        self._lock = threading.Lock()
        self._open = 0  # guarded-by: _lock
        self.max_connections = max(0, int(max_connections))  # 0 = uncapped
        self._gauge = metrics.gauge("frontend/open_connections")
        self._accepted = metrics.counter("frontend/conns_accepted")
        self._closed = metrics.counter("frontend/conns_closed")
        self._reaped = metrics.counter("frontend/conns_reaped")
        self._refused = metrics.counter("frontend/conns_refused")

    def try_admit(self) -> bool:
        """One accept's verdict.  False → the caller just closes the
        socket (counted refused) — no parser, no thread, no state."""
        with self._lock:
            if self.max_connections \
                    and self._open >= self.max_connections:
                admitted = False
            else:
                self._open += 1
                self._gauge.set(self._open)
                admitted = True
        if admitted:
            self._accepted.inc()
        else:
            self._refused.inc()
        return admitted

    def release(self, reaped: bool = False) -> None:
        with self._lock:
            self._open = max(0, self._open - 1)
            self._gauge.set(self._open)
        self._closed.inc()
        if reaped:
            self._reaped.inc()

    @property
    def open(self) -> int:
        with self._lock:
            return self._open


class _HTTPError(Exception):
    """Internal: carries an HTTP status + JSON body to the handler."""

    def __init__(self, status: int, message: str, **fields):
        super().__init__(message)
        self.status = status
        self.body = {"error": message, **fields}
        self.headers: Dict[str, str] = {}


def _jsonify(out):
    """Numpy output pytree → JSON-able (dict/list containers kept,
    arrays → nested lists)."""
    if isinstance(out, dict):
        return {k: _jsonify(v) for k, v in out.items()}
    if isinstance(out, (list, tuple)):
        return [_jsonify(v) for v in out]
    return np.asarray(out).tolist()


def _parse_inputs(obj):
    """JSON request value → numpy input pytree.  A JSON list is always
    ONE array; a dict maps leaf names to arrays (the only multi-leaf
    container JSON can express unambiguously)."""
    if isinstance(obj, dict):
        return {k: np.asarray(v) for k, v in obj.items()}
    return np.asarray(obj)


def _shed_error(e: ServiceOverloaded) -> _HTTPError:
    err = _HTTPError(429, str(e),
                     retry_after_ms=e.retry_after_ms,
                     queue_depth=e.queue_depth,
                     capacity=e.capacity)
    if e.retry_after_ms is not None:
        # HTTP Retry-After is whole seconds — ceil so a client that
        # honors it never retries early; the exact hint rides a
        # custom header
        err.headers["Retry-After"] = str(
            max(1, int(-(-e.retry_after_ms // 1000))))
        err.headers["X-Retry-After-Ms"] = f"{e.retry_after_ms:.1f}"
    return err


class FrontendServer:
    """One wire endpoint over a :class:`~bigdl_tpu.serving.
    ModelRegistry` and/or directly-attached backends.

    Parameters
    ----------
    registry:
        Optional :class:`~bigdl_tpu.serving.ModelRegistry`.  Requests
        resolve through latest-wins + breaker-fallback routing
        (``registry.route``), the resolved version is pinned for the
        exchange, and the outcome feeds that version's breaker.
    backends:
        ``{name: ReplicaSet | InferenceService}`` served directly (a
        ReplicaSet is the self-healing multi-replica path; version is
        reported as 0).  ``add_backend`` attaches more after start.
    qos:
        Optional :class:`~bigdl_tpu.frontend.qos.QosAdmission`.  Every
        request passes ``qos.admit(tenant)`` first; its per-tenant
        counters share this server's metric registry when it was built
        without one.
    port / host:
        ``port=0`` binds an ephemeral port (tests); ``port=None``
        resolves ``Config.frontend_port`` (0 = refuse to start — the
        frontend is opt-in).  Loopback-only by default.
    tracer:
        Optional :class:`~bigdl_tpu.telemetry.Tracer`: each exchange
        records a ``wire_request`` span (trace_id, tenant, model,
        rows, status).
    name:
        Admin-plane source name (metrics/tracer registered under it
        when the admin plane is up).
    auth_token:
        Bearer token every request must present
        (``Authorization: Bearer <token>``, constant-time compared;
        401 otherwise).  ``None`` resolves
        ``Config.frontend_auth_token`` / ``BIGDL_TPU_FRONTEND_AUTH_
        TOKEN``; empty keeps the historical open behavior — but a
        NON-loopback ``host`` is refused at construction without a
        token.
    """

    def __init__(self, registry=None, *, backends: Optional[dict] = None,
                 qos: Optional[QosAdmission] = None,
                 port: Optional[int] = 0, host: str = "127.0.0.1",
                 tracer=None, name: str = "frontend",
                 stream_window: int = 4,
                 auth_token: Optional[str] = None,
                 core: Optional[str] = None,
                 shards: Optional[int] = None,
                 max_connections: Optional[int] = None,
                 idle_timeout_s: Optional[float] = None,
                 reuse_port: bool = False,
                 pin_cpus: Optional[bool] = None):
        if port is None:
            from bigdl_tpu.utils.config import get_config
            port = int(getattr(get_config(), "frontend_port", 0) or 0)
            if port <= 0:
                raise ValueError(
                    "FrontendServer(port=None) with Config.frontend_port "
                    "unset — the wire frontend is opt-in; pass a port or "
                    "set BIGDL_TPU_FRONTEND_PORT")
        self.name = name
        self.host = host
        self.requested_port = int(port)
        self.port: Optional[int] = None
        self.registry = registry
        self.metrics = MetricRegistry()
        self.qos = qos if qos is not None \
            else QosAdmission(registry=self.metrics)
        if qos is not None and qos.registry is not self.metrics:
            # one /metrics page: fold the wire counters into the qos
            # registry rather than running two half-pages
            self.metrics = qos.registry
        self.tracer = tracer
        # auth/host validation FIRST — pure checks, before anything
        # with an external side effect (the admin-plane registration
        # below reserves a source name that only stop() releases; a
        # constructor that registers then raises would leak it)
        if auth_token is None:
            from bigdl_tpu.utils.config import get_config
            auth_token = getattr(get_config(), "frontend_auth_token",
                                 "") or ""
        self._auth_token = str(auth_token)
        if host not in ("127.0.0.1", "localhost", "::1"):
            if not self._auth_token:
                # the ROADMAP item-1 wire-auth gap: X-Tenant is a QoS
                # tag, not a credential — an open non-loopback bind
                # would hand the serving plane to the network.  Refuse
                # at construction, before any socket exists.
                raise ValueError(
                    f"refusing to bind non-loopback host {host!r} "
                    "without an auth token — set "
                    "Config.frontend_auth_token / "
                    "BIGDL_TPU_FRONTEND_AUTH_TOKEN (requests then "
                    "need `Authorization: Bearer <token>`) or bind "
                    "127.0.0.1")
            logger.warning(
                "wire frontend binding non-loopback host %r with "
                "bearer-token auth; X-Tenant remains a QoS tag, not a "
                "credential", host)
        self._stream_window = max(1, int(stream_window))
        # connection-core knobs (ROADMAP item 2): unset values resolve
        # Config — env-tunable without touching call sites
        from bigdl_tpu.utils.config import get_config
        _cfg = get_config()
        if core is None:
            core = getattr(_cfg, "frontend_core", "eventloop") \
                or "eventloop"
        if core not in ("eventloop", "threaded"):
            raise ValueError(f"unknown frontend core {core!r} — "
                             f"expected 'eventloop' or 'threaded'")
        self.core = core
        if shards is None:
            shards = int(getattr(_cfg, "frontend_shards", 1) or 1)
        self._shards = max(1, int(shards))
        if max_connections is None:
            max_connections = int(getattr(
                _cfg, "frontend_max_connections", 0) or 0)
        if idle_timeout_s is None:
            idle_timeout_s = float(getattr(
                _cfg, "frontend_idle_timeout_s", 0.0) or 0.0)
        self._idle_timeout_s = max(0.0, float(idle_timeout_s))
        self._reuse_port = bool(reuse_port)
        if pin_cpus is None:
            pin_cpus = bool(getattr(_cfg, "frontend_pin_cpus", False))
        self._pin_cpus = bool(pin_cpus)
        self._lock = threading.Lock()
        self._backends: Dict[str, object] = dict(backends or {})  # guarded-by: _lock
        self.inflight = _WireInflight()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._elc = None  # EventLoopCore when core="eventloop" is live
        # counters pre-created so a zero-traffic scrape shows the schema
        for c in ("requests", "responses_2xx", "responses_4xx",
                  "responses_5xx", "sheds", "deadline_504",
                  "stream_chunks", "generate_tokens",
                  "client_disconnects"):
            self.metrics.counter(f"frontend/{c}")
        self._latency_h = self.metrics.histogram("frontend/wire_latency_s")
        # connection-plane schema (gauge + counters) pre-created too
        self._conns = _ConnLedger(self.metrics, max_connections)
        # admin plane: the wire+tenant registry and the tracer scrape
        # from the same endpoint as everything else
        from bigdl_tpu.telemetry import admin as _admin
        self._admin_name: Optional[str] = None
        _srv = _admin.maybe_start()
        if _srv is not None:
            self._admin_name = _srv.unique_source_name(self.name)
            _srv.add_registry(self._admin_name, self.metrics)
            if self.tracer is not None:
                _srv.add_tracer(self._admin_name, self.tracer)

    # -- backends ----------------------------------------------------------
    def add_backend(self, name: str, backend) -> "FrontendServer":
        """Serve ``backend`` (ReplicaSet / InferenceService) as
        ``name`` alongside the registry's models.  Direct backends
        shadow same-named registry entries."""
        with self._lock:
            self._backends[name] = backend
        return self

    def remove_backend(self, name: str) -> None:
        with self._lock:
            self._backends.pop(name, None)

    def _resolve(self, name: str, version: Optional[int]):
        """(key, submit_target, breaker) for one wire exchange.  Direct
        backends pin version 0; registry names resolve through
        latest-wins + breaker fallback and pin the resolved version."""
        with self._lock:
            backend = self._backends.get(name)
            attached = sorted(self._backends)
        if backend is not None:
            return (name, 0), backend, None
        if self.registry is None:
            raise _HTTPError(404, f"no model {name!r} attached",
                             models=attached)
        try:
            v, svc, brk = self.registry.route(name, version)
        except KeyError as e:
            raise _HTTPError(404, str(e)) from None
        return (name, v), svc, brk

    # acquires: wire_inflight
    def _resolve_pinned(self, name: str, version: Optional[int]):
        """Resolve AND pin (wire-inflight enter) atomically enough for
        cutover: between ``route()`` and ``inflight.enter()`` a hot
        cutover could observe a zero count, drain, and undeploy the
        resolved version — so after entering, re-check the version is
        still deployed and re-resolve if not.  The caller owns the
        matching ``inflight.exit(key)``."""
        while True:
            key, backend, brk = self._resolve(name, version)
            self.inflight.enter(key)
            if brk is None:
                return key, backend, brk  # direct backend: no cutover
            try:
                self.registry.get(name, key[1])
                return key, backend, brk
            except KeyError:
                # undeployed in the race window: un-pin and re-resolve
                # (latest-wins now points at the successor)
                self.inflight.exit(key)
                if version is not None:
                    raise _HTTPError(
                        404, f"model {name!r} version {version} was "
                             f"undeployed") from None

    def models(self) -> dict:
        with self._lock:
            direct = {n: [0] for n in sorted(self._backends)}
        if self.registry is not None:
            for n, vs in self.registry.list_models().items():
                direct.setdefault(n, vs)
        return direct

    # -- cutover support ---------------------------------------------------
    def drain_version(self, name: str, version: int,
                      timeout: Optional[float] = None) -> bool:
        """Block until no wire request is pinned to
        ``name``:``version`` — the connection-draining half of hot
        cutover (:class:`~bigdl_tpu.frontend.cutover.HotCutover` calls
        this AFTER routing flipped to the new version, BEFORE the old
        one is undeployed).  True when drained, False on timeout."""
        return self.inflight.wait_idle((name, int(version)), timeout)

    # -- request plumbing (runs on handler threads) ------------------------
    @staticmethod
    def _submit(backend, x, deadline: Optional[float], ctx):
        """Uniform submit over the two backend shapes.  Returns a
        Future.  :class:`RequestSpecError` is the backend refusing the
        request's SHAPE (``_conform_request`` spec validation) — that
        is the client's fault, so it wraps to 400 here; any OTHER
        synchronous error (e.g. a deferred-spec warmup compile
        failure) and anything the future later resolves with stay
        server-side stories (500)."""
        from bigdl_tpu.resilience.replica_set import ReplicaSet
        try:
            if isinstance(backend, ReplicaSet):
                timeout = (None if deadline is None
                           else max(0.0, deadline - time.monotonic()))
                return backend.submit(x, timeout=timeout, ctx=ctx)
            return backend.submit(x, deadline=deadline, ctx=ctx)
        except RequestSpecError as e:
            raise _HTTPError(400, str(e)) from None

    @staticmethod
    def _backend_max_batch(backend) -> int:
        return int(backend.max_batch_size)

    def _predict_once(self, backend, x, deadline, ctx, brk):
        """One submit → result, with the breaker fed the outcome (the
        same contract ``ModelRegistry.submit`` keeps in-process)."""
        from bigdl_tpu.serving.registry import ModelRegistry
        try:
            fut = self._submit(backend, x, deadline, ctx)
        except ServiceOverloaded:
            raise  # never a breaker outcome (documented contract)
        try:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            out = self._result_or_504(fut, remaining)
        except BaseException as e:
            if not fut.cancelled():
                ModelRegistry.record_outcome(brk, e)
            raise
        ModelRegistry.record_outcome(brk, None)
        return out

    @staticmethod
    def _result_or_504(fut, timeout: Optional[float]):
        """``fut.result`` with the deadline-family normalization the
        ReplicaSet also does: an UNRESOLVED wait expiry (the request is
        still queued past its wire deadline) becomes
        :class:`DeadlineExceeded` (→ 504); a future that RESOLVED with
        its own timeout-family error propagates untouched (on py>=3.11
        ``FutureTimeoutError`` aliases ``TimeoutError``, so the two
        cases share an except clause)."""
        try:
            return fut.result(timeout)
        except FutureTimeoutError:
            if fut.done():
                raise  # the future's own DeadlineExceeded — real story
            fut.cancel()  # refuse late service; batcher honors cancel
            raise DeadlineExceeded(
                "wire deadline expired while the request was "
                "queued") from None

    @staticmethod
    def _parse_body(body: bytes, ctype: str):
        """Request body → ``(input_pytree, rows)`` — the one 400
        taxonomy both connection cores share."""
        if ctype == _NPY:
            try:
                x = np.load(BytesIO(body), allow_pickle=False)
            except (ValueError, OSError, EOFError,
                    zipfile.BadZipFile) as e:
                # the SPECIFIC malformed-bytes family np.load raises —
                # a blanket except here would 400 internal bugs too
                # (the GL302 taxonomy contract).  BadZipFile: a body
                # starting with zip magic routes np.load through
                # zipfile before any numpy validation
                raise _HTTPError(
                    400, f"unreadable npy body: {e}") from None
        else:
            try:
                payload = json.loads(body.decode("utf-8"))
            except ValueError as e:
                # JSONDecodeError and UnicodeDecodeError both subclass
                # ValueError — the whole malformed-body family
                raise _HTTPError(
                    400, f"unreadable JSON body: {e}") from None
            if not isinstance(payload, dict) or "inputs" not in payload:
                raise _HTTPError(
                    400, 'JSON body must be {"inputs": ...}')
            try:
                x = _parse_inputs(payload["inputs"])
            except (ValueError, TypeError) as e:
                # e.g. ragged nested lists np.asarray refuses
                raise _HTTPError(
                    400, f"unparseable inputs: {e}") from None
        try:
            leaves = ([x] if not isinstance(x, dict)
                      else list(x.values()))
            rows = int(leaves[0].shape[0])
        except (AttributeError, IndexError):
            raise _HTTPError(400, "inputs must have a leading batch "
                                  "dim") from None
        return x, rows

    @staticmethod
    def _parse_generate_body(body: bytes, ctype: str):
        """Generate request body → ``(prompt 1-D int array, max_new or
        None)``.  JSON only: ``{"prompt": [ints],
        "max_new_tokens": n?}`` — token streams have no npy bulk
        form."""
        if ctype == _NPY:
            raise _HTTPError(400, "generate takes a JSON body "
                                  '({"prompt": [...]}), not npy')
        try:
            payload = json.loads(body.decode("utf-8"))
        except ValueError as e:
            raise _HTTPError(
                400, f"unreadable JSON body: {e}") from None
        if not isinstance(payload, dict) or "prompt" not in payload:
            raise _HTTPError(400, 'JSON body must be {"prompt": ...}')
        try:
            prompt = np.asarray(payload["prompt"], dtype=np.int64)
        except (ValueError, TypeError) as e:
            raise _HTTPError(
                400, f"unparseable prompt: {e}") from None
        if prompt.ndim != 1 or prompt.size < 1:
            raise _HTTPError(400, "prompt must be a non-empty 1-D "
                                  "token list")
        max_new = payload.get("max_new_tokens")
        if max_new is not None:
            if not isinstance(max_new, int) or max_new < 1:
                raise _HTTPError(
                    400, f"max_new_tokens must be a positive int, got "
                         f"{max_new!r}")
        return prompt, max_new

    def _run_generate(self, handler, name, version, body, ctype,
                      tenant, deadline_ms, trace_id) -> None:
        """The whole exchange for one POST .../generate — the decode
        twin of :meth:`_run_predict` (same QoS admission, pinning,
        cutover-retry and accounting shape)."""
        t0 = time.monotonic()
        self.metrics.counter("frontend/requests").inc()
        self.qos.admit(tenant)
        deadline = (t0 + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        ctx = RequestContext(trace_id=trace_id, tenant=tenant,
                             deadline=deadline)
        self._resolve(name, version)  # 404 precedence
        prompt, max_new = self._parse_generate_body(body, ctype)
        ok = False
        try:
            for attempt in range(3):
                key, backend, brk = self._resolve_pinned(name, version)
                try:  # pin held: EVERY exit path below must unpin
                    if not getattr(backend, "is_decode_backend",
                                   False):
                        raise _HTTPError(
                            400, f"model {name!r} is not a decode "
                                 f"backend — use /predict")
                    ok = self._respond_generate(
                        handler, key, backend, prompt, max_new,
                        deadline, ctx, brk)
                    break
                except ServiceClosed:
                    # cutover closed the pinned version before any
                    # token was streamed — re-resolve the successor
                    # (same idempotency argument as _run_predict)
                    if attempt == 2 or version is not None:
                        raise
                finally:
                    self.inflight.exit(key)
        finally:
            self.qos.record_result(tenant, time.monotonic() - t0, ok)
            self._latency_h.observe(time.monotonic() - t0)

    def _respond_generate(self, handler, key, backend, prompt,
                          max_new, deadline, ctx, brk) -> bool:
        """Token streaming for one decode request: ndjson over chunked
        transfer, one ``{"index", "token"}`` line per generated token
        IN ORDER, closed by a ``{"done": true, "tokens": [...]}``
        trailer carrying the full sequence.  The 200 chunked header is
        committed only at the FIRST token, so pre-stream failures
        (shed, deadline, cutover close) still get their real status.
        The decode scheduler thread hands tokens to this handler
        thread through a Queue — ``on_token`` never blocks the
        scheduler on a slow reader."""
        import queue as _queue

        from bigdl_tpu.serving.registry import ModelRegistry
        _name, version = key
        started = [False]

        def ensure_started():
            if not started[0]:
                handler.start_chunked(
                    200, _NDJSON,
                    {"X-Trace-Id": ctx.trace_id,
                     "X-Model-Version": str(version)})
                started[0] = True

        tokens_q: "_queue.Queue" = _queue.Queue()

        def on_token(index: int, token: int) -> None:
            tokens_q.put((index, int(token)))

        try:
            fut = backend.submit(prompt, max_new_tokens=max_new,
                                 deadline=deadline, ctx=ctx,
                                 on_token=on_token)
        except RequestSpecError as e:
            raise _HTTPError(400, str(e)) from None
        # ServiceOverloaded propagates untouched (never a breaker
        # outcome — same contract as _predict_once)
        sent = 0

        def stream_line(index: int, token: int) -> None:
            ensure_started()
            handler.send_chunk(json.dumps(
                {"index": index, "token": token}).encode() + b"\n")

        try:
            while not fut.done():
                try:
                    idx, tok = tokens_q.get(timeout=0.05)
                except _queue.Empty:
                    if deadline is not None \
                            and time.monotonic() >= deadline \
                            and fut.cancel():
                        # still queued past the wire deadline: refuse
                        # late service (a running sequence is failed
                        # by the scheduler's own deadline check)
                        raise DeadlineExceeded(
                            "wire deadline expired while the prompt "
                            "was queued")
                    continue
                stream_line(idx, tok)
                sent += 1
            # every token is enqueued before the future settles, so a
            # final non-blocking drain empties the stream
            while True:
                try:
                    idx, tok = tokens_q.get_nowait()
                except _queue.Empty:
                    break
                stream_line(idx, tok)
                sent += 1
            try:
                res = self._result_or_504(fut, 0)  # done: no block
            except BaseException as e:
                if not fut.cancelled():
                    ModelRegistry.record_outcome(brk, e)
                raise
            ModelRegistry.record_outcome(brk, None)
            ensure_started()
            handler.send_chunk(json.dumps(
                {"done": True,
                 "tokens": [int(t) for t in res.tokens],
                 "n": len(res.tokens),
                 "finish_reason": res.finish_reason,
                 "trace_id": ctx.trace_id}).encode() + b"\n")
            self._count_status(200)
            self.metrics.counter("frontend/generate_tokens").inc(sent)
            return True
        except BaseException as e:
            fut.cancel()
            if not started[0]:
                raise  # real status (and the cutover retry) upstream
            if isinstance(e, ConnectionError):
                self.metrics.counter(
                    "frontend/client_disconnects").inc()
                return False
            status, body_, _hdrs = self._classify(e)
            if status >= 500 and status != 504 \
                    and not isinstance(e, _HTTPError):
                logger.exception(
                    "frontend mid-generate 5xx after %d tokens", sent)
            self._count_status(status)
            try:
                handler.send_chunk(json.dumps(
                    {"error": body_["error"], "status": status,
                     "tokens_streamed": sent}).encode() + b"\n")
            except ConnectionError:
                pass
            return False
        finally:
            if started[0]:
                try:
                    handler.end_chunked()
                except ConnectionError:
                    pass

    def _run_predict(self, handler, name, version, body, ctype,
                     accept, tenant, deadline_ms, trace_id) -> None:
        """The whole exchange for one POST .../predict."""
        t0 = time.monotonic()
        self.metrics.counter("frontend/requests").inc()
        self.qos.admit(tenant)  # raises 429/403 before any queue touch
        deadline = (t0 + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        ctx = RequestContext(trace_id=trace_id, tenant=tenant,
                             deadline=deadline)
        key, backend, brk = self._resolve(name, version)
        x, rows = self._parse_body(body, ctype)
        ok = False
        try:
            for attempt in range(3):
                key, backend, brk = self._resolve_pinned(name, version)
                try:  # pin held: EVERY exit path below must unpin
                    max_batch = self._backend_max_batch(backend)
                    if rows <= max_batch:
                        out = self._predict_once(backend, x, deadline,
                                                 ctx, brk)
                        self._respond_single(handler, key, ctx, out,
                                             accept)
                        ok = True
                    else:
                        ok = self._respond_stream(
                            handler, key, backend, x, rows, max_batch,
                            deadline, ctx, brk)
                    break
                except ServiceClosed:
                    # the pinned version closed under us — only a
                    # cutover racing the pin can do that, and nothing
                    # was served yet (an accepted request drains before
                    # close): re-resolve onto the successor.  Inference
                    # is idempotent, so the retry is safe.
                    if attempt == 2 or version is not None:
                        raise
                finally:
                    self.inflight.exit(key)
        finally:
            self.qos.record_result(tenant, time.monotonic() - t0, ok)
            self._latency_h.observe(time.monotonic() - t0)

    def _respond_single(self, handler, key, ctx, out, accept) -> None:
        name, version = key
        headers = {"X-Trace-Id": ctx.trace_id,
                   "X-Model-Version": str(version)}
        if accept == _NPY and isinstance(out, np.ndarray):
            buf = BytesIO()
            np.save(buf, out, allow_pickle=False)
            handler.send_body(200, buf.getvalue(), _NPY, headers)
            return
        body = json.dumps({
            "model": name, "version": version,
            "trace_id": ctx.trace_id,
            "outputs": _jsonify(out)}).encode("utf-8")
        handler.send_body(200, body, "application/json", headers)

    def _respond_stream(self, handler, key, backend, x, rows,
                        max_batch, deadline, ctx, brk) -> bool:
        """Chunked ndjson for a multi-chunk predict: bounded in-flight
        submission window, one line per chunk in input order.  The 200
        chunked header is committed only when the FIRST chunk result
        is ready — a failure before that (expired deadline, sustained
        overload, a cutover closing the pinned version) propagates to
        the caller and gets its REAL status code (504/429/503 with
        Retry-After et al.) instead of a 200 wrapping an error line;
        after commitment, a mid-stream failure terminates the stream
        with an ``error`` line (the client sees exactly which offset
        failed).  Returns whether the whole stream completed.  Exactly
        ONE response status is counted, here."""
        name, version = key
        started = [False]

        def ensure_started():
            if not started[0]:
                handler.start_chunked(
                    200, _NDJSON,
                    {"X-Trace-Id": ctx.trace_id,
                     "X-Model-Version": str(version)})
                started[0] = True

        def leaf_slice(lo, hi):
            if isinstance(x, dict):
                return {k: v[lo:hi] for k, v in x.items()}
            return x[lo:hi]

        def remaining():
            if deadline is None:
                return None
            return max(0.0, deadline - time.monotonic())

        inflight = []  # [(offset, n, future)]
        sent = 0
        stalls = 0
        try:
            for off in range(0, rows, max_batch):
                hi = min(off + max_batch, rows)
                chunk = leaf_slice(off, hi)
                while True:
                    if deadline is not None \
                            and time.monotonic() >= deadline:
                        raise DeadlineExceeded(
                            f"deadline passed after {sent} of {rows} "
                            f"rows streamed")
                    try:
                        fut = self._submit(backend, chunk, deadline,
                                           ctx)
                        inflight.append((off, hi - off, fut))
                        stalls = 0
                        break
                    except ServiceOverloaded as e:
                        if inflight:
                            sent += self._flush_one(handler, inflight,
                                                    remaining(), brk,
                                                    ensure_started)
                            continue
                        # foreign traffic owns the queue: honor the
                        # drain hint briefly instead of hot-spinning,
                        # but give up eventually on a deadline-less
                        # stream rather than parking a server thread
                        # forever
                        stalls += 1
                        if deadline is None and stalls > 200:
                            raise
                        time.sleep(min(0.05, (e.retry_after_ms or 10.0)
                                       / 1e3))
                while len(inflight) >= self._stream_window:
                    sent += self._flush_one(handler, inflight,
                                            remaining(), brk,
                                            ensure_started)
            while inflight:
                sent += self._flush_one(handler, inflight, remaining(),
                                        brk, ensure_started)
            ensure_started()  # unreachable-empty guard: rows >= 2 chunks
            handler.send_chunk(json.dumps(
                {"done": True, "rows": sent,
                 "trace_id": ctx.trace_id}).encode() + b"\n")
            self._count_status(200)
            return True
        except BaseException as e:
            # cancel FIRST: the commonest mid-stream failure is the
            # client hanging up, in which case the error-line write
            # below raises too — the backlog must not keep occupying
            # backend queue slots for a request nobody is reading
            for _off, _n, fut in inflight:
                fut.cancel()
            if not started[0]:
                # nothing sent yet: the caller can still answer with
                # the REAL status code (and _run_predict's cutover
                # retry on ServiceClosed still applies)
                raise
            if isinstance(e, ConnectionError):
                # the client hung up mid-stream — THEIR outcome, not a
                # server fault: no traceback, and no responses_5xx
                # (which would corrupt the 5xx SLO signal on every
                # reset); a dedicated counter keeps it observable
                self.metrics.counter(
                    "frontend/client_disconnects").inc()
                return False
            status, body, _hdrs = self._classify(e)
            if status >= 500 and status != 504 \
                    and not isinstance(e, _HTTPError):
                # same contract as do_POST's 5xx path: an internal bug
                # after the 200 header is committed must still leave a
                # traceback, not vanish into an ndjson error line
                logger.exception(
                    "frontend mid-stream 5xx after %d rows", sent)
            self._count_status(status)
            try:
                handler.send_chunk(json.dumps(
                    {"error": body["error"], "status": status,
                     "rows_streamed": sent}).encode() + b"\n")
            except ConnectionError:
                pass  # client already gone
            return False
        finally:
            if started[0]:
                try:
                    handler.end_chunked()
                except ConnectionError:
                    pass

    def _flush_one(self, handler, inflight, timeout, brk,
                   ensure_started) -> int:
        """Resolve the OLDEST in-flight chunk and stream its line (the
        200 chunked header is committed here, by the FIRST result —
        see _respond_stream)."""
        from bigdl_tpu.serving.registry import ModelRegistry
        off, n, fut = inflight.pop(0)
        try:
            out = self._result_or_504(fut, timeout)
        except BaseException as e:
            if not fut.cancelled():
                ModelRegistry.record_outcome(brk, e)
            raise
        ModelRegistry.record_outcome(brk, None)
        ensure_started()
        handler.send_chunk(json.dumps(
            {"offset": off, "rows": n,
             "outputs": _jsonify(out)}).encode() + b"\n")
        self.metrics.counter("frontend/stream_chunks").inc()
        return n

    # -- error mapping -----------------------------------------------------
    @staticmethod
    def _classify(e: BaseException):
        """Exception → (status, json_body, headers)."""
        if isinstance(e, _HTTPError):
            return e.status, e.body, e.headers
        if isinstance(e, ServiceOverloaded):  # incl. TenantRateLimited
            err = _shed_error(e)
            return err.status, err.body, err.headers
        if isinstance(e, DeadlineExceeded):
            return 504, {"error": str(e)}, {}
        if isinstance(e, UnknownTenantError):
            return 403, {"error": str(e)}, {}
        if isinstance(e, ServiceClosed):
            return 503, {"error": str(e)}, {}
        # NO blanket ValueError/TypeError → 400: client-driven parse
        # and validation errors are wrapped in _HTTPError where they
        # are raised, so an unexpected one here is a server bug that
        # must report 500 and hit the 5xx traceback log, not hide as
        # a client error
        return 500, {"error": f"{type(e).__name__}: {e}"}, {}

    def _count_status(self, status: int) -> None:
        if status == 429:
            self.metrics.counter("frontend/sheds").inc()
        if status == 504:
            self.metrics.counter("frontend/deadline_504").inc()
        bucket = f"responses_{status // 100}xx"
        self.metrics.counter(f"frontend/{bucket}").inc()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        """Bind + serve; idempotent.  Returns the bound port.  The
        ``core`` knob picks the connection core: ``"eventloop"`` (the
        default — a few selector loop threads own every socket,
        optionally SO_REUSEPORT-sharded) or ``"threaded"`` (the PR-14
        thread-per-connection stdlib core).  Both speak the identical
        wire surface."""
        if self._httpd is not None or self._elc is not None:
            return self.port
        if self.core == "eventloop":
            from bigdl_tpu.frontend.eventloop import EventLoopCore
            self._elc = EventLoopCore(
                self, host=self.host, port=self.requested_port,
                shards=self._shards, reuse_port=self._reuse_port,
                idle_timeout_s=self._idle_timeout_s,
                pin_cpus=self._pin_cpus)
            self.port = self._elc.start()
            logger.info(
                "wire frontend listening on http://%s:%d "
                "(event-loop core, %d shard(s); POST "
                "/v1/models/<name>/predict)", self.host, self.port,
                self._shards)
            return self.port
        return self._start_threaded()

    def _start_threaded(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: keep-alive + chunked transfer encoding (the
            # streaming predict path needs it); every non-chunked
            # response therefore MUST carry Content-Length
            protocol_version = "HTTP/1.1"
            # buffered response writes + TCP_NODELAY: the stdlib
            # default (unbuffered wfile) emits every header line as
            # its own segment, and Nagle + delayed-ACK turns that
            # into ~40 ms per exchange on loopback — measured by the
            # bench's wire_overhead_ms before this pair of lines
            wbufsize = 64 * 1024
            disable_nagle_algorithm = True
            # idle keep-alive connections die after this many seconds
            # (the threaded twin of the event-loop core's reaper; None
            # keeps the historical wait-forever behavior)
            timeout = server._idle_timeout_s or None

            def log_message(self, fmt, *args):
                logger.debug("frontend: " + fmt, *args)

            def finish(self):
                try:
                    super().finish()
                finally:
                    # admitted in verify_request; released exactly once
                    # per connection, however the handler exits
                    server._conns.release()

            # -- response primitives the server methods drive ----------
            def send_body(self, status, body: bytes, ctype: str,
                          headers: Optional[dict] = None) -> None:
                server._count_status(status)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)
                self.wfile.flush()  # buffered wfile + keep-alive

            def send_json(self, status, obj,
                          headers: Optional[dict] = None) -> None:
                self.send_body(status, json.dumps(obj).encode(),
                               "application/json", headers)

            def start_chunked(self, status, ctype,
                              headers: Optional[dict] = None) -> None:
                # status accounting happens at stream END (success or
                # error line) — see _respond_stream
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Transfer-Encoding", "chunked")
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()

            def send_chunk(self, data: bytes) -> None:
                if data:
                    self.wfile.write(
                        f"{len(data):x}\r\n".encode() + data + b"\r\n")
                    self.wfile.flush()  # stream lines land promptly

            def end_chunked(self) -> None:
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()

            def check_auth(self) -> bool:
                """True when no token is configured (historical open
                loopback) or the request carries the right bearer.
                Refuses with 401 BEFORE the body is read (so the
                connection closes — the 411/413 keep-alive desync
                guard) and never echoes the expected token."""
                tok = server._auth_token
                if not tok:
                    return True
                hdr = self.headers.get("Authorization", "")
                if hdr.startswith("Bearer ") and hmac.compare_digest(
                        hdr[len("Bearer "):].strip(), tok):
                    return True
                self.close_connection = True  # body (if any) unread
                try:
                    self.send_json(
                        401, {"error": "missing or invalid bearer "
                                       "token"},
                        {"WWW-Authenticate": "Bearer"})
                except ConnectionError:
                    pass
                return False

            # -- routes -------------------------------------------------
            def do_GET(self):  # noqa: N802 - stdlib API
                if not self.check_auth():
                    return
                if self.path == "/v1/models":
                    self.send_json(200, {"models": server.models()})
                else:
                    self.send_json(404, {
                        "error": f"no route {self.path}",
                        "routes": ["/v1/models",
                                   "POST /v1/models/<name>[:<v>]"
                                   "/predict",
                                   "POST /v1/models/<name>[:<v>]"
                                   "/generate"]})

            def do_POST(self):  # noqa: N802 - stdlib API
                if not self.check_auth():
                    return
                m = _PREDICT_RE.match(self.path)
                gen = None if m is not None \
                    else _GENERATE_RE.match(self.path)
                if m is None and gen is None:
                    # the request body is never read on this path — a
                    # keep-alive stream would parse it as the next
                    # request line, so close (same guard as 411/413)
                    self.close_connection = True
                    self.send_json(404, {"error": f"no route "
                                                  f"{self.path}"})
                    return
                body_read = False
                try:
                    te = (self.headers.get("Transfer-Encoding")
                          or "").strip().lower()
                    if te:
                        # chunked request bodies: drive the SAME
                        # incremental de-chunker the event-loop parser
                        # embeds over this core's blocking rfile
                        from bigdl_tpu.frontend.http1 import (
                            ProtocolError, read_chunked_body)
                        if self.headers.get("Content-Length") \
                                is not None:
                            raise _HTTPError(
                                400, "both Content-Length and "
                                     "Transfer-Encoding present")
                        if te != "chunked":
                            raise _HTTPError(
                                501, f"unsupported transfer coding "
                                     f"{te!r}")
                        try:
                            body = read_chunked_body(self.rfile,
                                                     _MAX_BODY)
                        except ProtocolError as e:
                            raise _HTTPError(e.status,
                                             str(e)) from None
                    else:
                        try:
                            length = int(self.headers.get(
                                "Content-Length", -1))
                        except ValueError:
                            raise _HTTPError(
                                400, "unreadable "
                                     "Content-Length") from None
                        if length < 0:
                            raise _HTTPError(
                                411, "Content-Length required")
                        if length > _MAX_BODY:
                            raise _HTTPError(
                                413, f"body of {length} bytes exceeds "
                                     f"the {_MAX_BODY} byte cap")
                        body = self.rfile.read(length)
                    body_read = True
                    deadline_ms = self.headers.get("X-Deadline-Ms")
                    if deadline_ms is not None:
                        try:
                            deadline_ms = float(deadline_ms)
                        except ValueError:
                            raise _HTTPError(
                                400, f"bad X-Deadline-Ms "
                                     f"{deadline_ms!r}") from None
                    route = m if m is not None else gen
                    version = route.group("version")
                    ctype = (self.headers.get("Content-Type") or
                             "").split(";")[0].strip().lower()
                    if m is not None:
                        server._traced_predict(
                            self, m.group("name"),
                            int(version) if version else None, body,
                            ctype,
                            (self.headers.get("Accept") or
                             "").split(",")[0].strip().lower(),
                            self.headers.get("X-Tenant"), deadline_ms,
                            self.headers.get("X-Trace-Id"))
                    else:
                        server._traced_generate(
                            self, gen.group("name"),
                            int(version) if version else None, body,
                            ctype, self.headers.get("X-Tenant"),
                            deadline_ms,
                            self.headers.get("X-Trace-Id"))
                except ConnectionError:
                    # client went away mid-exchange (pipe break OR
                    # hard reset) — nothing to send, and letting it
                    # escape would have socketserver print a traceback
                    # per reset
                    pass
                except BaseException as e:
                    status, body_, hdrs = server._classify(e)
                    if status >= 500 and status != 504 \
                            and not isinstance(e, _HTTPError):
                        # 504 is a client-driven outcome (its own
                        # counter tracks it), not a server fault worth
                        # a traceback per expiry
                        logger.exception("frontend 5xx on %s",
                                         self.path)
                    if not body_read:
                        # the request body is still sitting unread on
                        # the keep-alive stream (411/413 reject) — a
                        # persistent connection would parse it as the
                        # next request line, so close instead
                        self.close_connection = True
                    try:
                        self.send_json(status, body_, hdrs)
                    except ConnectionError:
                        pass

        class _Httpd(ThreadingHTTPServer):
            daemon_threads = True
            # socketserver's default backlog of 5 SYN-drops any
            # connect burst; keep the threaded baseline comparable in
            # the bench connection sweep
            request_queue_size = 1024

            def verify_request(self, request, client_address):
                # the hard connection cap, enforced BEFORE a handler
                # thread is spawned — socketserver closes the refused
                # socket itself (the cheap-refusal contract both cores
                # share)
                return server._conns.try_admit()

        self._httpd = _Httpd(
            (self.host, self.requested_port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="bigdl-tpu-frontend", daemon=True)
        self._thread.start()
        logger.info("wire frontend listening on http://%s:%d "
                    "(POST /v1/models/<name>/predict)", self.host,
                    self.port)
        return self.port

    def _traced_predict(self, handler, name, version, body, ctype,
                        accept, tenant, deadline_ms, trace_id) -> None:
        tracer = self.tracer
        if tracer is None or not tracer.enabled:
            self._run_predict(handler, name, version, body, ctype,
                              accept, tenant, deadline_ms, trace_id)
            return
        if trace_id is None:
            # mint HERE, not later in the RequestContext, so the
            # wire_request span carries the id — otherwise stories for
            # clients that sent no X-Trace-Id would be missing their
            # wire hop (the id still flows down and is echoed)
            from bigdl_tpu.telemetry.context import new_trace_id
            trace_id = new_trace_id()
        status_box = {"status": 200}
        try:
            with tracer.span("wire_request", cat="serving",
                             model=name, tenant=tenant,
                             trace_id=trace_id):
                try:
                    self._run_predict(handler, name, version, body,
                                      ctype, accept, tenant,
                                      deadline_ms, trace_id)
                except BaseException as e:
                    status_box["status"] = self._classify(e)[0]
                    raise
        finally:
            if status_box["status"] != 200:
                tracer.instant("wire_error", cat="serving",
                               model=name, tenant=tenant,
                               status=status_box["status"])

    def _traced_generate(self, handler, name, version, body, ctype,
                         tenant, deadline_ms, trace_id) -> None:
        """Span-wrapping twin of :meth:`_traced_predict` for the
        generate route (same mint-here trace-id reasoning)."""
        tracer = self.tracer
        if tracer is None or not tracer.enabled:
            self._run_generate(handler, name, version, body, ctype,
                               tenant, deadline_ms, trace_id)
            return
        if trace_id is None:
            from bigdl_tpu.telemetry.context import new_trace_id
            trace_id = new_trace_id()
        status_box = {"status": 200}
        try:
            with tracer.span("wire_request", cat="serving",
                             model=name, tenant=tenant,
                             trace_id=trace_id):
                try:
                    self._run_generate(handler, name, version, body,
                                       ctype, tenant, deadline_ms,
                                       trace_id)
                except BaseException as e:
                    status_box["status"] = self._classify(e)[0]
                    raise
        finally:
            if status_box["status"] != 200:
                tracer.instant("wire_error", cat="serving",
                               model=name, tenant=tenant,
                               status=status_box["status"])

    @property
    def running(self) -> bool:
        if self._elc is not None:
            return self._elc.running
        return self._thread is not None and self._thread.is_alive()

    @property
    def open_connections(self) -> int:
        """Live connection count (same number the
        ``frontend/open_connections`` gauge exports)."""
        return self._conns.open

    def url(self, path: str = "/") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def stop(self) -> None:
        elc, self._elc = self._elc, None
        if elc is not None:
            elc.stop()
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._admin_name is not None:
            from bigdl_tpu.telemetry import admin as _admin
            _srv = _admin.current()
            if _srv is not None:
                _srv.remove_source(self._admin_name)
            self._admin_name = None

    def __enter__(self) -> "FrontendServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
