"""Incremental HTTP/1.1 request framing for the event-loop front end.

The parser half of the C100K wire plane (ROADMAP item 2): a pure,
allocation-light state machine the loop core feeds raw socket bytes —
no file objects, no blocking reads, no threads.  ``feed()`` only
appends; ``head()`` / ``poll()`` advance the machine and either return
parsed structures, return ``None`` (need more bytes — the slow-loris
case: a byte-dribbled request line parks the CONNECTION, never a
thread or a loop tick), or raise :class:`ProtocolError` carrying the
HTTP status the connection should die with.  Body framing is
Content-Length only — the same surface the threaded core speaks
(chunked REQUEST bodies were never accepted there either; the value is
validated and refused at the exchange layer so the 400/411/413 error
taxonomy matches the threaded core byte for byte).

Keep-alive semantics follow the RFC defaults the stdlib handler uses:
HTTP/1.1 persists unless ``Connection: close``; HTTP/1.0 closes unless
``Connection: keep-alive``.  After ``poll()`` returns a complete
request the parser is immediately ready for the next one on the same
buffer, so pipelined bytes are never mis-framed (the keep-alive desync
guard, now at the parser layer).

Separated from the loop so the robustness tests can drive it
byte-at-a-time without sockets (``tests/test_frontend_eventloop.py``).
"""

from __future__ import annotations

from http.client import responses as _REASONS
from typing import Dict, Optional

# caps: a request head (line + headers) past this size is a client
# error (431), not a reason to buffer unboundedly — the slow-loris
# memory bound for the head phase
MAX_HEAD_BYTES = 64 << 10


class ProtocolError(Exception):
    """Unrecoverable wire-level framing error: respond ``status`` (if
    anything can still be written) and close — re-synchronizing a
    stream after a malformed head is guesswork."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class Request:
    """One parsed request.  ``headers`` keys are lowercased; ``body``
    is filled by ``poll()`` (empty until then)."""

    __slots__ = ("method", "target", "version", "headers", "keep_alive",
                 "body")

    def __init__(self, method: str, target: str, version: str,
                 headers: Dict[str, str], keep_alive: bool):
        self.method = method
        self.target = target
        self.version = version
        self.headers = headers
        self.keep_alive = keep_alive
        self.body = b""

    def get(self, name: str, default=None):
        return self.headers.get(name.lower(), default)


def _body_length(headers: Dict[str, str]) -> int:
    """Framing length from Content-Length.  Missing / unparseable /
    negative values frame as ZERO body — the exchange layer then
    answers the threaded core's exact 411/400 and closes, so the bogus
    framing never reaches a next request."""
    cl = headers.get("content-length")
    if cl is None:
        return 0
    try:
        n = int(cl.strip())
    except ValueError:
        return 0
    return n if n > 0 else 0


class RequestParser:
    """Incremental request parser: ``feed(bytes)`` → ``head()`` /
    ``poll()``.  Once a :class:`ProtocolError` is raised the parser is
    poisoned (every later call re-raises): the connection is done."""

    def __init__(self, max_head: int = MAX_HEAD_BYTES):
        self._max_head = int(max_head)
        self._buf = bytearray()
        self._head: Optional[Request] = None
        self._body_len = 0
        self._error: Optional[ProtocolError] = None

    def feed(self, data: bytes) -> None:
        """Append raw socket bytes.  Never raises — errors surface
        from ``head()``/``poll()`` so the reader's fast path stays
        branch-free."""
        if self._error is None and data:
            self._buf += data

    def buffered(self) -> int:
        return len(self._buf)

    def head(self) -> Optional[Request]:
        """The current request's head once its header block is
        complete (body may still be arriving), else ``None``.  Lets
        the exchange layer run must-happen-before-body checks (auth,
        411/413) without waiting for — or ever reading — the body."""
        if self._error is not None:
            raise self._error
        if self._head is None:
            self._parse_head()
        return self._head

    def poll(self) -> Optional[Request]:
        """A COMPLETE request (head + Content-Length body) or
        ``None``; returning one resets the machine for the next
        request on the same connection."""
        req = self.head()
        if req is None or len(self._buf) < self._body_len:
            return None
        req.body = bytes(self._buf[:self._body_len])
        del self._buf[:self._body_len]
        self._head = None
        self._body_len = 0
        return req

    # -- internals ---------------------------------------------------------
    def _fail(self, status: int, message: str):
        self._error = ProtocolError(status, message)
        self._buf.clear()
        raise self._error

    def _parse_head(self) -> None:
        # tolerate a stray CRLF preamble between keep-alive requests
        # (RFC 9112 §2.2) — some clients flush one after a body
        while self._buf[:2] == b"\r\n":
            del self._buf[:2]
        end = self._buf.find(b"\r\n\r\n")
        if end < 0:
            if len(self._buf) > self._max_head:
                self._fail(431, f"request head exceeds the "
                                f"{self._max_head} byte cap")
            return
        if end > self._max_head:
            self._fail(431, f"request head exceeds the "
                            f"{self._max_head} byte cap")
        block = bytes(self._buf[:end])
        del self._buf[:end + 4]
        lines = block.decode("latin-1").split("\r\n")
        parts = lines[0].split()
        if len(parts) != 3:
            self._fail(400, f"malformed request line {lines[0]!r}")
        method, target, version = parts
        if version not in ("HTTP/1.0", "HTTP/1.1"):
            self._fail(505, f"unsupported protocol version {version!r}")
        headers: Dict[str, str] = {}
        last: Optional[str] = None
        for ln in lines[1:]:
            if ln[:1] in (" ", "\t") and last is not None:
                # obs-fold continuation: join with a space (RFC 9112)
                headers[last] += " " + ln.strip()
                continue
            name, sep, value = ln.partition(":")
            if not sep or not name or name.strip() != name:
                # whitespace before the colon is a smuggling classic —
                # refuse rather than guess (matches RFC 9112 §5.1 MUST)
                self._fail(400, f"malformed header line {ln!r}")
            last = name.lower()
            headers[last] = value.strip()
        conn_toks = headers.get("connection", "").lower()
        keep_alive = ("close" not in conn_toks if version == "HTTP/1.1"
                      else "keep-alive" in conn_toks)
        self._head = Request(method, target, version, headers,
                             keep_alive)
        self._body_len = _body_length(headers)


# -- response encoding (the write half of the wire) ------------------------
def render_head(status: int, headers=None, *,
                content_length: Optional[int] = None,
                chunked: bool = False, close: bool = False) -> bytes:
    """Serialize one response head.  Exactly one framing mode: chunked
    OR Content-Length (every non-chunked response MUST carry one —
    keep-alive clients frame the next response off it)."""
    reason = _REASONS.get(status, "")
    lines = [f"HTTP/1.1 {status} {reason}".rstrip()]
    for k, v in (headers or {}).items():
        lines.append(f"{k}: {v}")
    if chunked:
        lines.append("Transfer-Encoding: chunked")
    elif content_length is not None:
        lines.append(f"Content-Length: {content_length}")
    if close:
        lines.append("Connection: close")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")


def encode_chunk(data: bytes) -> bytes:
    """One chunked-transfer frame (empty payloads encode to nothing —
    a zero-length chunk would terminate the stream)."""
    if not data:
        return b""
    return f"{len(data):x}\r\n".encode("latin-1") + data + b"\r\n"


CHUNK_TRAILER = b"0\r\n\r\n"
