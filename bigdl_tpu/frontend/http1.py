"""Incremental HTTP/1.1 request framing for the event-loop front end.

The parser half of the C100K wire plane (ROADMAP item 2): a pure,
allocation-light state machine the loop core feeds raw socket bytes —
no file objects, no blocking reads, no threads.  ``feed()`` only
appends; ``head()`` / ``poll()`` advance the machine and either return
parsed structures, return ``None`` (need more bytes — the slow-loris
case: a byte-dribbled request line parks the CONNECTION, never a
thread or a loop tick), or raise :class:`ProtocolError` carrying the
HTTP status the connection should die with.  Body framing is
Content-Length or ``Transfer-Encoding: chunked``: chunked request
bodies are de-chunked INCREMENTALLY by :class:`ChunkedDecoder` — one
state machine shared by both connection cores (this parser embeds it;
the threaded core drives the same machine over its blocking ``rfile``
via :func:`read_chunked_body`) — with malformed chunk framing answered
400 and the total de-chunked body bounded (413, the body-phase twin of
the 431 head cap, so a chunk stream can't buffer unboundedly).

Keep-alive semantics follow the RFC defaults the stdlib handler uses:
HTTP/1.1 persists unless ``Connection: close``; HTTP/1.0 closes unless
``Connection: keep-alive``.  After ``poll()`` returns a complete
request the parser is immediately ready for the next one on the same
buffer, so pipelined bytes are never mis-framed (the keep-alive desync
guard, now at the parser layer).

Separated from the loop so the robustness tests can drive it
byte-at-a-time without sockets (``tests/test_frontend_eventloop.py``).
"""

from __future__ import annotations

from http.client import responses as _REASONS
from typing import Dict, Optional

# caps: a request head (line + headers) past this size is a client
# error (431), not a reason to buffer unboundedly — the slow-loris
# memory bound for the head phase
MAX_HEAD_BYTES = 64 << 10

# chunk-size lines are tiny (hex length + optional extensions); a line
# past this is framing garbage, not a big chunk
MAX_CHUNK_LINE = 256

# default total-body cap for chunked requests — matches the frontend's
# Content-Length 413 cap so the two framing modes share one bound
MAX_BODY_BYTES = 256 << 20


class ProtocolError(Exception):
    """Unrecoverable wire-level framing error: respond ``status`` (if
    anything can still be written) and close — re-synchronizing a
    stream after a malformed head is guesswork."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class Request:
    """One parsed request.  ``headers`` keys are lowercased; ``body``
    is filled by ``poll()`` (empty until then)."""

    __slots__ = ("method", "target", "version", "headers", "keep_alive",
                 "body")

    def __init__(self, method: str, target: str, version: str,
                 headers: Dict[str, str], keep_alive: bool):
        self.method = method
        self.target = target
        self.version = version
        self.headers = headers
        self.keep_alive = keep_alive
        self.body = b""

    def get(self, name: str, default=None):
        return self.headers.get(name.lower(), default)


def _body_length(headers: Dict[str, str]) -> int:
    """Framing length from Content-Length.  Missing / unparseable /
    negative values frame as ZERO body — the exchange layer then
    answers the threaded core's exact 411/400 and closes, so the bogus
    framing never reaches a next request."""
    cl = headers.get("content-length")
    if cl is None:
        return 0
    try:
        n = int(cl.strip())
    except ValueError:
        return 0
    return n if n > 0 else 0


class ChunkedDecoder:
    """Incremental ``Transfer-Encoding: chunked`` request-body decoder —
    the ONE chunk-framing state machine both connection cores share.
    The event-loop :class:`RequestParser` embeds it (feed bytes, poll);
    the threaded core drives the same instance over its blocking
    ``rfile`` through :func:`read_chunked_body`.

    ``feed(bytes)`` appends; ``poll()`` advances the machine and
    returns the complete de-chunked body once the terminal chunk and
    its (discarded) trailer section arrive, else ``None``.  Malformed
    framing raises :class:`ProtocolError` 400; a stream whose
    de-chunked total exceeds ``max_body`` raises 413 — the body-phase
    twin of the head's 431 cap.  Bytes past the body's end (pipelined
    next request) stay in ``residual()``.
    """

    __slots__ = ("_max_body", "_buf", "_body", "_mode", "_remaining")

    def __init__(self, max_body: int = MAX_BODY_BYTES):
        self._max_body = int(max_body)
        self._buf = bytearray()
        self._body = bytearray()
        # size → data → crlf → size … → trailer → (returns)
        self._mode = "size"
        self._remaining = 0

    def feed(self, data: bytes) -> None:
        if data:
            self._buf += data

    def residual(self) -> bytes:
        """Unconsumed bytes past the body's end (only meaningful after
        ``poll()`` returned the body)."""
        return bytes(self._buf)

    # hints for a BLOCKING driver (read_chunked_body): what to read next
    def wants_line(self) -> bool:
        return self._mode != "data"

    def bytes_needed(self) -> int:
        """In data mode: exact payload bytes still owed to the current
        chunk (drivers may read less; never read more than this plus
        the trailing CRLF)."""
        return self._remaining

    def _take_line(self, cap: int) -> Optional[str]:
        nl = self._buf.find(b"\n")
        if nl < 0:
            if len(self._buf) > cap:
                raise ProtocolError(
                    400, "malformed chunk framing: oversized line")
            return None
        if nl > cap:
            raise ProtocolError(
                400, "malformed chunk framing: oversized line")
        line = bytes(self._buf[:nl])
        del self._buf[:nl + 1]
        return line.rstrip(b"\r").decode("latin-1")

    def poll(self) -> Optional[bytes]:
        while True:
            if self._mode == "size":
                line = self._take_line(MAX_CHUNK_LINE)
                if line is None:
                    return None
                # chunk extensions (";ext=val") are legal; discard them
                size_tok = line.split(";", 1)[0].strip()
                try:
                    n = int(size_tok, 16)
                except ValueError:
                    raise ProtocolError(
                        400, f"malformed chunk framing: bad chunk size "
                             f"{size_tok!r}") from None
                if n < 0:
                    raise ProtocolError(
                        400, "malformed chunk framing: negative size")
                if n == 0:
                    self._mode = "trailer"
                    continue
                if len(self._body) + n > self._max_body:
                    raise ProtocolError(
                        413, f"chunked body exceeds the "
                             f"{self._max_body} byte cap")
                self._remaining = n
                self._mode = "data"
            elif self._mode == "data":
                if not self._buf:
                    return None
                take = min(len(self._buf), self._remaining)
                self._body += self._buf[:take]
                del self._buf[:take]
                self._remaining -= take
                if self._remaining:
                    return None
                self._mode = "crlf"
            elif self._mode == "crlf":
                # each chunk's payload is followed by a bare CRLF
                line = self._take_line(2)
                if line is None:
                    return None
                if line:
                    raise ProtocolError(
                        400, "malformed chunk framing: missing chunk "
                             "terminator")
                self._mode = "size"
            else:  # trailer: zero or more fields, then an empty line
                line = self._take_line(MAX_CHUNK_LINE)
                if line is None:
                    return None
                if line:
                    continue  # trailer field — legal, discarded
                body = bytes(self._body)
                self._body.clear()
                return body


def read_chunked_body(rfile, max_body: int = MAX_BODY_BYTES) -> bytes:
    """Drive :class:`ChunkedDecoder` over a BLOCKING file-like (the
    threaded core's buffered ``rfile``) — same state machine, same 400 /
    413 taxonomy as the event-loop core.  Reads exactly the body's
    bytes: size/terminator/trailer lines via bounded ``readline`` and
    chunk payloads via exact-length ``read``, so pipelined keep-alive
    bytes after the body are never consumed."""
    dec = ChunkedDecoder(max_body)
    while True:
        body = dec.poll()
        if body is not None:
            return body
        if dec.wants_line():
            # +1 for the \n; a line hitting the cap without one is
            # flagged by the decoder itself
            data = rfile.readline(MAX_CHUNK_LINE + 2)
        else:
            data = rfile.read(min(dec.bytes_needed(), 64 << 10))
        if not data:
            raise ProtocolError(400, "truncated chunked body")
        dec.feed(data)


class RequestParser:
    """Incremental request parser: ``feed(bytes)`` → ``head()`` /
    ``poll()``.  Once a :class:`ProtocolError` is raised the parser is
    poisoned (every later call re-raises): the connection is done."""

    def __init__(self, max_head: int = MAX_HEAD_BYTES,
                 max_body: int = MAX_BODY_BYTES):
        self._max_head = int(max_head)
        self._max_body = int(max_body)
        self._buf = bytearray()
        self._head: Optional[Request] = None
        self._body_len = 0
        self._chunked: Optional[ChunkedDecoder] = None
        self._error: Optional[ProtocolError] = None

    def feed(self, data: bytes) -> None:
        """Append raw socket bytes.  Never raises — errors surface
        from ``head()``/``poll()`` so the reader's fast path stays
        branch-free."""
        if self._error is None and data:
            self._buf += data

    def buffered(self) -> int:
        return len(self._buf)

    def head(self) -> Optional[Request]:
        """The current request's head once its header block is
        complete (body may still be arriving), else ``None``.  Lets
        the exchange layer run must-happen-before-body checks (auth,
        411/413) without waiting for — or ever reading — the body."""
        if self._error is not None:
            raise self._error
        if self._head is None:
            self._parse_head()
        return self._head

    def poll(self) -> Optional[Request]:
        """A COMPLETE request (head + body, Content-Length or chunked
        framing) or ``None``; returning one resets the machine for the
        next request on the same connection."""
        req = self.head()
        if req is None:
            return None
        if self._chunked is not None:
            # hand every buffered byte to the shared chunk machine;
            # whatever follows the body comes back via residual()
            self._chunked.feed(bytes(self._buf))
            self._buf.clear()
            try:
                body = self._chunked.poll()
            except ProtocolError as e:
                self._fail(e.status, str(e))
            if body is None:
                return None
            self._buf += self._chunked.residual()
            req.body = body
            self._head = None
            self._chunked = None
            return req
        if len(self._buf) < self._body_len:
            return None
        req.body = bytes(self._buf[:self._body_len])
        del self._buf[:self._body_len]
        self._head = None
        self._body_len = 0
        return req

    # -- internals ---------------------------------------------------------
    def _fail(self, status: int, message: str):
        self._error = ProtocolError(status, message)
        self._buf.clear()
        raise self._error

    def _parse_head(self) -> None:
        # tolerate a stray CRLF preamble between keep-alive requests
        # (RFC 9112 §2.2) — some clients flush one after a body
        while self._buf[:2] == b"\r\n":
            del self._buf[:2]
        end = self._buf.find(b"\r\n\r\n")
        if end < 0:
            if len(self._buf) > self._max_head:
                self._fail(431, f"request head exceeds the "
                                f"{self._max_head} byte cap")
            return
        if end > self._max_head:
            self._fail(431, f"request head exceeds the "
                            f"{self._max_head} byte cap")
        block = bytes(self._buf[:end])
        del self._buf[:end + 4]
        lines = block.decode("latin-1").split("\r\n")
        parts = lines[0].split()
        if len(parts) != 3:
            self._fail(400, f"malformed request line {lines[0]!r}")
        method, target, version = parts
        if version not in ("HTTP/1.0", "HTTP/1.1"):
            self._fail(505, f"unsupported protocol version {version!r}")
        headers: Dict[str, str] = {}
        last: Optional[str] = None
        for ln in lines[1:]:
            if ln[:1] in (" ", "\t") and last is not None:
                # obs-fold continuation: join with a space (RFC 9112)
                headers[last] += " " + ln.strip()
                continue
            name, sep, value = ln.partition(":")
            if not sep or not name or name.strip() != name:
                # whitespace before the colon is a smuggling classic —
                # refuse rather than guess (matches RFC 9112 §5.1 MUST)
                self._fail(400, f"malformed header line {ln!r}")
            last = name.lower()
            headers[last] = value.strip()
        conn_toks = headers.get("connection", "").lower()
        keep_alive = ("close" not in conn_toks if version == "HTTP/1.1"
                      else "keep-alive" in conn_toks)
        self._head = Request(method, target, version, headers,
                             keep_alive)
        te = headers.get("transfer-encoding", "").lower().strip()
        if te:
            # a CL alongside TE is the request-smuggling classic
            # (RFC 9112 §6.1 MUST treat as an error); any coding other
            # than a single terminal "chunked" we don't implement
            if "content-length" in headers:
                self._fail(400, "both Content-Length and "
                                "Transfer-Encoding present")
            if te != "chunked":
                self._fail(501, f"unsupported transfer coding {te!r}")
            self._body_len = 0
            self._chunked = ChunkedDecoder(self._max_body)
        else:
            self._body_len = _body_length(headers)
            self._chunked = None


# -- response encoding (the write half of the wire) ------------------------
def render_head(status: int, headers=None, *,
                content_length: Optional[int] = None,
                chunked: bool = False, close: bool = False) -> bytes:
    """Serialize one response head.  Exactly one framing mode: chunked
    OR Content-Length (every non-chunked response MUST carry one —
    keep-alive clients frame the next response off it)."""
    reason = _REASONS.get(status, "")
    lines = [f"HTTP/1.1 {status} {reason}".rstrip()]
    for k, v in (headers or {}).items():
        lines.append(f"{k}: {v}")
    if chunked:
        lines.append("Transfer-Encoding: chunked")
    elif content_length is not None:
        lines.append(f"Content-Length: {content_length}")
    if close:
        lines.append("Connection: close")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")


def encode_chunk(data: bytes) -> bytes:
    """One chunked-transfer frame (empty payloads encode to nothing —
    a zero-length chunk would terminate the stream)."""
    if not data:
        return b""
    return f"{len(data):x}\r\n".encode("latin-1") + data + b"\r\n"


CHUNK_TRAILER = b"0\r\n\r\n"
