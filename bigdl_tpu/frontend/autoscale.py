"""Load-driven replica autoscaling over a ReplicaSet.

ROADMAP 1d: the :class:`~bigdl_tpu.resilience.ReplicaSet` already
records the signals (per-replica queue depth, the batcher's
seconds-per-request drain EWMA, batch occupancy); PR 14 adds the
actuator (``ReplicaSet.set_replica_count``) and this controller to
close the loop.

**Load signal.**  Per active replica::

    busy_i = min(1, queue_depth_i * drain_ewma_s_i / horizon_s)

— the estimated seconds of backlog in replica *i*'s queue, normalized
by the sampling horizon: ``busy = 1`` means the replica holds at least
one full sampling interval's worth of work (saturated).  Before the
first dispatch (no EWMA yet) the fallback is ``queue_depth /
max_batch_size`` — "queued dispatches", the pure queue-depth signal.
The set-level load is the mean over active replicas, so it is
comparable across replica counts (load 0.5 at 2 replicas and at 6
replicas mean the same per-replica pressure).

**Controller.**  Deliberately boring — hysteresis + cooldown, the
thing every production autoscaler converges to:

- scale UP by one replica after ``up_consecutive`` consecutive samples
  with ``load >= high_watermark``;
- scale DOWN by one after ``down_consecutive`` consecutive samples
  with ``load <= low_watermark`` (down is slower than up by default:
  adding capacity late costs SLO, removing it late costs only money);
- never within ``cooldown_s`` of the previous action (a grow's warmup
  + queue redistribution must settle before the signal is trusted
  again), never outside ``[min_replicas, max_replicas]``.

``step()`` is the whole brain and takes an injectable ``now`` — unit
tests drive spike/decay scenarios deterministically with a fake clock
and never sleep.  ``start()`` wraps it in a daemon sampling thread for
production (``bench.py --serving`` wire mode proves a live spike scales
up within the cooldown budget and back down when load subsides).

Scale actions run ON the controller thread and block it (a grow pays
AOT bucket warmup) — by design: while capacity is changing, sampling
is paused, which is exactly what the cooldown would enforce anyway.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

logger = logging.getLogger("bigdl_tpu.frontend")


class ReplicaAutoscaler:
    """See module docstring.  ``registry`` defaults to the replica
    set's own, so ``frontend/autoscale_*`` counters and the
    ``frontend/replicas`` / ``frontend/load`` gauges scrape from the
    same ``/metrics`` source as the ``resilience/*`` family."""

    def __init__(self, replica_set, *, min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 high_watermark: float = 0.75,
                 low_watermark: float = 0.15,
                 interval_s: float = 0.25,
                 up_consecutive: int = 2,
                 down_consecutive: int = 4,
                 cooldown_s: float = 2.0,
                 horizon_s: Optional[float] = None,
                 scale_timeout_s: float = 30.0,
                 registry=None, clock=time.monotonic):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1: {min_replicas}")
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas {max_replicas} < min_replicas "
                f"{min_replicas}")
        if not (0.0 <= low_watermark < high_watermark):
            raise ValueError(
                f"need 0 <= low_watermark < high_watermark, got "
                f"{low_watermark} / {high_watermark}")
        self.rs = replica_set
        self.min_replicas = int(min_replicas)
        self.max_replicas = (int(max_replicas)
                             if max_replicas is not None else None)
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.interval_s = float(interval_s)
        self.up_consecutive = max(1, int(up_consecutive))
        self.down_consecutive = max(1, int(down_consecutive))
        self.cooldown_s = float(cooldown_s)
        self.horizon_s = (float(horizon_s) if horizon_s is not None
                          else self.interval_s)
        self.scale_timeout_s = float(scale_timeout_s)
        self.registry = (registry if registry is not None
                         else replica_set.registry)
        self._clock = clock
        # controller state: only step() mutates it, and step() is
        # serialized by _step_lock (the sampling thread and a test
        # driving step() directly must not interleave half-updates)
        self._step_lock = threading.Lock()
        self._above = 0                    # guarded-by: _step_lock
        self._below = 0                    # guarded-by: _step_lock
        # guarded-by: _step_lock
        self._last_action_t: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for c in ("autoscale_up", "autoscale_down"):
            self.registry.counter(f"frontend/{c}")
        self.registry.gauge("frontend/replicas").set(
            replica_set.n_replicas)

    # -- signal ------------------------------------------------------------
    def load(self) -> float:
        """Mean per-replica busyness in [0, 1] (module docstring)."""
        ixs = self.rs.active_indices()
        if not ixs:
            return 0.0
        total = 0.0
        for i in ixs:
            svc = self.rs.replica(i)
            depth = svc.queue_depth()
            spr = svc.drain_ewma_s
            if spr is not None:
                busy = depth * spr / max(self.horizon_s, 1e-6)
            else:
                busy = depth / max(1, svc.max_batch_size)
            total += min(1.0, busy)
        return total / len(ixs)

    # -- controller --------------------------------------------------------
    def step(self, now: Optional[float] = None) -> dict:
        """One sample → maybe one scale action.  Returns the decision
        record (load, counts, action taken) — what the sampling thread
        logs and what tests assert on."""
        if now is None:
            now = self._clock()
        with self._step_lock:
            load = self.load()
            self.registry.gauge("frontend/load").set(round(load, 4))
            n = self.rs.n_replicas
            self._above = self._above + 1 \
                if load >= self.high_watermark else 0
            self._below = self._below + 1 \
                if load <= self.low_watermark else 0
            action = None
            in_cooldown = (
                self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s)
            cap = self.max_replicas
            if not in_cooldown:
                if self._above >= self.up_consecutive \
                        and (cap is None or n < cap):
                    action = "up"
                elif self._below >= self.down_consecutive \
                        and n > self.min_replicas:
                    action = "down"
            if action is not None:
                target = n + 1 if action == "up" else n - 1
                # the scale call blocks this thread (grow pays AOT
                # warmup; shrink drains a backlog) — sampling pausing
                # while capacity changes is intended (see module
                # docstring); no autoscaler lock is held around it
                # beyond the step serialization.  The timeout is
                # mandatory here: an unbounded shrink onto a WEDGED
                # replica would park this thread (and the set's scale
                # lock) forever — the stranded sweep past the deadline
                # is exactly the escape hatch set_replica_count
                # provides
                self.rs.set_replica_count(
                    target, timeout=self.scale_timeout_s)
                self.registry.counter(
                    f"frontend/autoscale_{action}").inc()
                self.registry.gauge("frontend/replicas").set(
                    self.rs.n_replicas)
                self._last_action_t = now
                self._above = self._below = 0
                logger.info("autoscale %s: %d -> %d (load %.3f)",
                            self.rs.name, n, target, load)
            return {"load": round(load, 4), "replicas":
                    self.rs.n_replicas, "action": action,
                    "above": self._above, "below": self._below,
                    "in_cooldown": in_cooldown}

    # -- sampling thread ---------------------------------------------------
    def start(self) -> "ReplicaAutoscaler":
        """Run ``step()`` every ``interval_s`` on a daemon thread;
        idempotent."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"{self.rs.name}-autoscaler",
                daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:
                # a scale failure (e.g. device OOM on grow) must not
                # kill the controller — the next sample retries
                logger.exception("autoscaler step failed on %s",
                                 self.rs.name)
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "ReplicaAutoscaler":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
