"""Drain-free hot version cutover: deploy under load, drop nothing.

ROADMAP 1a named the gap precisely: latest-wins routing already
consults per-version circuit breakers (PR 10), and
``ModelRegistry.undeploy(drain=True)`` drains a service's own queue —
but nothing coordinated the WIRE: a wire request that resolved version
N (and pinned it for a multi-chunk stream) could lose its service to an
undeploy racing the exchange.  :class:`HotCutover` sequences a deploy
so that never happens:

1. **Warm before flip.**  ``registry.deploy`` AOT-compiles every row
   bucket inside the service constructor and only then inserts the new
   version into latest-wins routing — version N keeps serving the whole
   time (this ordering is PR 5's; the cutover leans on it).  When the
   caller passes no ``input_spec``, the incumbent's warmed row spec is
   reused so the new version never warms on live traffic.
2. **Flip.**  The instant the deploy lands, new wire requests resolve
   N+1 (``FrontendServer`` pins the resolved version per exchange).
3. **Drain the wire.**  ``frontend.drain_version(name, N)`` blocks
   until zero wire requests are still pinned to N — including
   mid-stream chunked predicts.
4. **Drain the queue, then drop.**  ``registry.undeploy(name, N,
   drain=True)`` lets version N's batcher finish every accepted
   in-process request before the service stops.

The zero-dropped-requests guarantee is gated in
``tests/test_frontend.py`` (N hot deploys under sustained wire load,
every accepted request resolves correctly) and measured by
``bench.py --serving``'s wire mode.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

logger = logging.getLogger("bigdl_tpu.frontend")


class CutoverDrainTimeout(RuntimeError):
    """Wire connections to the outgoing version did not drain inside
    the budget; the old version was NOT undeployed (it keeps serving
    its stragglers — retry or undeploy manually)."""


class HotCutover:
    """Deploy coordinator over a :class:`~bigdl_tpu.serving.
    ModelRegistry` and (optionally) the :class:`~bigdl_tpu.frontend.
    FrontendServer` in front of it.

    Without a frontend the wire-drain step is skipped (there is no
    wire) and the cutover degrades to warm-deploy + queue-drain — the
    in-process contract PR 5 already kept.
    """

    def __init__(self, registry, frontend=None, *,
                 drain_timeout_s: float = 30.0):
        self.registry = registry
        self.frontend = frontend
        self.drain_timeout_s = float(drain_timeout_s)

    def deploy(self, name: str, model=None, *,
               undeploy_old: bool = True,
               drain_timeout_s: Optional[float] = None,
               **deploy_kw) -> dict:
        """Hot-deploy ``model`` as the next version of ``name`` (all
        ``ModelRegistry.deploy`` kwargs pass through) and retire the
        incumbent without dropping a request.  Returns a report dict
        (old/new versions, warmup + drain seconds, whether the old
        version was undeployed)."""
        timeout = (self.drain_timeout_s if drain_timeout_s is None
                   else float(drain_timeout_s))
        old = self.registry.latest_version(name)
        if old is not None and "input_spec" not in deploy_kw \
                and "service" not in deploy_kw:
            # (a prebuilt `service=` deploy owns its own warmup — an
            # inherited input_spec doesn't apply to it)
            # reuse the incumbent's warmed row spec so the new version
            # AOT-warms at deploy instead of on live traffic
            spec = self.registry.get(name, old).row_spec
            if spec is not None:
                deploy_kw["input_spec"] = spec
        t0 = time.monotonic()
        self.registry.deploy(name, model, **deploy_kw)
        warmup_s = time.monotonic() - t0
        new = self.registry.latest_version(name)
        report = {"model": name, "old_version": old,
                  "new_version": new,
                  "warmup_s": round(warmup_s, 4),
                  "wire_drained": None, "wire_drain_s": None,
                  "old_undeployed": False}
        if old is None:
            return report  # first deploy: nothing to drain
        t1 = time.monotonic()
        if self.frontend is not None:
            drained = self.frontend.drain_version(name, old,
                                                  timeout=timeout)
            report["wire_drained"] = drained
            report["wire_drain_s"] = round(time.monotonic() - t1, 4)
            if not drained:
                # the old version still carries live wire exchanges —
                # dropping it now would break the zero-drop guarantee,
                # so it stays deployed (new traffic already routes to
                # the new version)
                raise CutoverDrainTimeout(
                    f"{name}:v{old} still has "
                    f"{self.frontend.inflight.count((name, old))} wire "
                    f"request(s) in flight after {timeout:.1f}s; old "
                    f"version left deployed")
        if undeploy_old:
            # queue-drain inside: every accepted in-process request on
            # the old version resolves before its batcher stops
            self.registry.undeploy(name, old, drain=True)
            report["old_undeployed"] = True
        logger.info("hot cutover %s: v%s -> v%s (warmup %.3fs, wire "
                    "drain %s)", name, old, new,
                    warmup_s, report["wire_drain_s"])
        return report
