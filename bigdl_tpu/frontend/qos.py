"""Per-tenant QoS admission: classes, token buckets, preemption ranks.

Reference: BigDL 2.0 Cluster Serving shares one cluster across tenants
through Redis queues (arXiv:2204.01715 §3.1) but has no admission
control; the TensorFlow-Serving lineage (arXiv:1605.08695 §4) treats
per-caller isolation as table stakes.  Here the wire frontend admits
every request through ONE :class:`QosAdmission`:

- **Tenants declare a QoS class** — ``"latency"`` (interactive SLO
  traffic) or ``"batch"`` (throughput backfill).  The class feeds the
  batcher's ``priority_fn`` (:meth:`QosAdmission.priority_fn`): under
  queue pressure (more rows queued than one dispatch carries — the
  existing queue-depth signal) latency-class requests preempt batch
  backlog in the coalescing order; under light load the hook is inert
  and order stays FIFO (``serving/batcher.RequestBatcher``).
- **Token-bucket rate limits** per tenant (``rate_rps`` requests/sec
  sustained, ``burst`` bucket depth).  An over-budget request is shed
  at ADMISSION — before it can occupy queue capacity — with
  :class:`TenantRateLimited` carrying ``retry_after_ms`` (when the
  bucket refills enough for one request), which the wire maps to HTTP
  429 + ``Retry-After`` exactly like a queue overload.
- **Per-tenant metrics** land in the shared
  :class:`~bigdl_tpu.telemetry.registry.MetricRegistry` under
  ``serving/tenant=<t>/{requests,shed,failed}`` counters and a
  ``serving/tenant=<t>/latency_s`` histogram, so a ``/metrics`` scrape
  renders per-tenant quantiles with zero extra bookkeeping.  Tenant
  names are declared up front; undeclared tenants fold into the
  ``_other`` bucket (bounded metric cardinality — a caller cannot mint
  unbounded counter names by spamming ``X-Tenant`` headers).

Everything here is host-side bookkeeping — no jax import, no device
work (the telemetry-package discipline).  Clocks are injectable so the
bucket math unit-tests without sleeping.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Iterable, Optional

from bigdl_tpu.serving.batcher import ServiceOverloaded
from bigdl_tpu.telemetry.registry import MetricRegistry

#: QoS classes, in preemption order (lower rank dispatches first)
LATENCY = "latency"
BATCH = "batch"
_RANKS = {LATENCY: 0, BATCH: 1}

#: metric-name bucket for tenants nobody declared (cardinality bound)
OTHER_TENANT = "_other"


class TenantRateLimited(ServiceOverloaded):
    """A tenant exceeded its declared token-bucket budget.  Subclasses
    :class:`~bigdl_tpu.serving.ServiceOverloaded` so every existing
    shed path (HTTP 429 + ``Retry-After``, client backoff loops,
    breaker exemption — overloads are never poison evidence) applies
    unchanged; ``queue_depth``/``capacity`` report the bucket fill."""

    def __init__(self, tenant: str, retry_after_ms: Optional[float]):
        super().__init__(0, 0, model=f"tenant:{tenant}",
                         retry_after_ms=retry_after_ms)
        self.tenant = tenant


class UnknownTenantError(PermissionError):
    """Strict-mode admission refused an undeclared tenant (HTTP 403)."""


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One declared tenant: QoS class + rate budget.

    ``rate_rps <= 0`` means unlimited (no bucket is even consulted);
    ``burst`` is the bucket depth — how far above the sustained rate a
    tenant may spike before shedding (default: one second's worth of
    budget, at least 1 request).
    """

    name: str
    qos_class: str = LATENCY
    rate_rps: float = 0.0
    burst: Optional[float] = None

    def __post_init__(self):
        if self.qos_class not in _RANKS:
            raise ValueError(
                f"tenant {self.name!r}: qos_class must be "
                f"'{LATENCY}' or '{BATCH}', got {self.qos_class!r}")
        if self.burst is not None and self.burst < 1:
            raise ValueError(
                f"tenant {self.name!r}: burst must be >= 1")

    @property
    def rank(self) -> int:
        return _RANKS[self.qos_class]

    @property
    def bucket_depth(self) -> float:
        if self.burst is not None:
            return float(self.burst)
        return max(1.0, float(self.rate_rps))


class TokenBucket:
    """Classic token bucket: ``depth`` tokens max, refilled at ``rate``
    tokens/sec.  ``try_take`` returns None on success or the
    milliseconds until one token is available (the retry-after hint).
    Thread-safe; ``clock`` injectable for deterministic tests."""

    def __init__(self, rate: float, depth: float, clock=time.monotonic):
        self.rate = float(rate)
        self.depth = float(depth)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.depth          # guarded-by: _lock
        self._t_last = self._clock()       # guarded-by: _lock

    def try_take(self, n: float = 1.0,
                 now: Optional[float] = None) -> Optional[float]:
        with self._lock:
            # clock read INSIDE the lock, and _t_last only moves
            # forward: two concurrent admits reading the clock outside
            # could commit their refills out of order, rewinding
            # _t_last and re-crediting already-spent refill time (a
            # tenant could sustainably exceed its declared rate)
            if now is None:
                now = self._clock()
            if now > self._t_last:
                self._tokens = min(
                    self.depth,
                    self._tokens + (now - self._t_last) * self.rate)
                self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return None
            deficit = n - self._tokens
            return round(deficit / self.rate * 1e3, 1)

    def tokens(self, now: Optional[float] = None) -> float:
        """Current fill (refilled to ``now``) — tests/dashboards."""
        with self._lock:
            if now is None:
                now = self._clock()
            elapsed = max(0.0, now - self._t_last)
            return min(self.depth, self._tokens + elapsed * self.rate)


class QosAdmission:
    """The frontend's per-tenant admission gate + metrics ledger.

    Parameters
    ----------
    tenants:
        Iterable of :class:`TenantSpec` (or plain dicts with the same
        fields).  Undeclared tenants are admitted with ``default``'s
    class/budget and metered under the ``_other`` bucket — unless
        ``strict=True``, where they are refused
        (:class:`UnknownTenantError` → HTTP 403 at the wire).  Strict
        refuses TENANTLESS requests (no ``X-Tenant`` header) too:
        omitting the header is not a way around the gate.
    default:
        The :class:`TenantSpec` applied to undeclared tenants and to
        tenantless requests (no ``X-Tenant`` header) when ``strict``
        is off.  Defaults to an unlimited latency-class spec.
    registry:
        The :class:`MetricRegistry` per-tenant counters land in (the
        frontend shares its own, so one ``/metrics`` page carries wire
        + tenant series).  A fresh registry is minted when omitted.
    clock:
        Injectable monotonic clock shared by every bucket.
    """

    def __init__(self, tenants: Iterable = (), *,
                 default: Optional[TenantSpec] = None,
                 strict: bool = False,
                 registry: Optional[MetricRegistry] = None,
                 clock=time.monotonic):
        self.registry = (registry if registry is not None
                         else MetricRegistry())
        self.strict = bool(strict)
        self.default = default if default is not None \
            else TenantSpec("default")
        self._clock = clock
        self._specs: Dict[str, TenantSpec] = {}
        self._buckets: Dict[str, TokenBucket] = {}
        for t in tenants:
            if isinstance(t, dict):
                t = TenantSpec(**t)
            if t.name in self._specs:
                raise ValueError(f"tenant {t.name!r} declared twice")
            self._specs[t.name] = t
            if t.rate_rps > 0:
                self._buckets[t.name] = TokenBucket(
                    t.rate_rps, t.bucket_depth, clock=clock)
        # one SHARED bucket meters all undeclared/tenantless traffic
        # when the default spec carries a budget (per-unknown-name
        # buckets would let a caller dodge the limit by rotating names)
        self._default_bucket = (
            TokenBucket(self.default.rate_rps,
                        self.default.bucket_depth, clock=clock)
            if self.default.rate_rps > 0 else None)
        # counters pre-created for every DECLARED tenant plus _other so
        # a zero-traffic scrape still shows the full tenant schema
        for name in (*self._specs, OTHER_TENANT):
            for c in ("requests", "shed", "failed"):
                self.registry.counter(f"serving/tenant={name}/{c}")

    # -- lookup ------------------------------------------------------------
    def spec(self, tenant: Optional[str]) -> TenantSpec:
        if tenant is None:
            return self.default
        return self._specs.get(tenant, self.default)

    def _metric_tenant(self, tenant: Optional[str]) -> str:
        """Metric-name bucket: declared tenants keep their name,
        everything else (incl. tenantless) folds into ``_other`` so
        arbitrary ``X-Tenant`` headers cannot mint unbounded series."""
        if tenant is not None and tenant in self._specs:
            return tenant
        return OTHER_TENANT

    # -- admission ---------------------------------------------------------
    def admit(self, tenant: Optional[str],
              now: Optional[float] = None) -> TenantSpec:
        """Admission verdict for one wire request.  Returns the
        tenant's spec on success; raises :class:`TenantRateLimited`
        (shed — counted) or, under ``strict``,
        :class:`UnknownTenantError` for undeclared AND tenantless
        requests."""
        mt = self._metric_tenant(tenant)
        if self.strict and tenant not in self._specs:
            # tenantless requests are refused too: omitting X-Tenant
            # must not be a cheaper path through a strict gate than
            # sending an undeclared one.  The message never enumerates
            # declared tenant names — X-Tenant is a tag, not a
            # credential, so listing valid tags on a 403 would hand an
            # unauthenticated caller the exact bypass for the gate
            if tenant is None:
                raise UnknownTenantError(
                    "request carries no tenant and admission is "
                    "strict — send X-Tenant with a declared tenant")
            raise UnknownTenantError(
                f"tenant {tenant!r} is not declared and admission is "
                f"strict")
        spec = self.spec(tenant)
        if tenant is not None and tenant in self._specs:
            # declared: its own bucket, or None when unlimited
            bucket = self._buckets.get(tenant)
        else:
            bucket = self._default_bucket
        if bucket is not None:
            wait_ms = bucket.try_take(1.0, now=now)
            if wait_ms is not None:
                self.registry.counter(
                    f"serving/tenant={mt}/shed").inc()
                raise TenantRateLimited(tenant, wait_ms)
        self.registry.counter(f"serving/tenant={mt}/requests").inc()
        return spec

    def record_result(self, tenant: Optional[str], latency_s: float,
                      ok: bool) -> None:
        """Per-tenant completion bookkeeping (the wire calls this once
        per request, shed requests excluded — those counted at
        admission)."""
        mt = self._metric_tenant(tenant)
        if not ok:
            self.registry.counter(f"serving/tenant={mt}/failed").inc()
        self.registry.histogram(
            f"serving/tenant={mt}/latency_s").observe(latency_s)

    # -- batcher hook ------------------------------------------------------
    def priority_fn(self, req) -> int:
        """The ``RequestBatcher`` preemption hook: rank of one queued
        ``_Request`` from its context's tenant tag (no context / no
        tenant → the default spec's class).  Wiring is the deploy
        owner's job: pass ``priority_fn=qos.priority_fn`` when
        constructing the ``InferenceService`` / ``ReplicaSet`` (or via
        ``ModelRegistry.deploy(..., priority_fn=...)``) — the
        ``FrontendServer`` does not own deploys and cannot inject it."""
        ctx = getattr(req, "ctx", None)
        tenant = getattr(ctx, "tenant", None) if ctx is not None \
            else None
        return self.spec(tenant).rank

    def snapshot(self) -> dict:
        """JSON-able view for dashboards/tests."""
        now = self._clock()
        return {
            "strict": self.strict,
            "tenants": {
                name: {"qos_class": s.qos_class,
                       "rate_rps": s.rate_rps,
                       "tokens": (round(self._buckets[name].tokens(now), 3)
                                  if name in self._buckets else None)}
                for name, s in sorted(self._specs.items())},
            "default": {"qos_class": self.default.qos_class,
                        "rate_rps": self.default.rate_rps},
        }
